"""Chrome/Perfetto trace export (docs/telemetry.md §trace export) — default
OFF, zero cost unless called.

Joins three record streams onto one navigable timeline (chrome://tracing /
https://ui.perfetto.dev, "Trace Event Format" JSON):

* **flight events** (``telemetry/flightrec.py``) — instant events on the
  ``flight events`` track, monotonic-stamped at the source; the
  ``step_begin``/``step_end`` pair per captured call is also the *anchor*
  that places the other two streams on the absolute axis;
* **host step phases** (``StepRecord`` — dataloader-wait / assembly /
  trace / compile / dispatch ms) — complete ("X") events on the ``host
  phases`` track, laid out inside the step's flight window in phase order
  (dataloader wait sits *before* the begin stamp: it was paid between
  calls);
* **device op timelines** (``DeviceStepRecord.top_ops`` from the sampled
  profiler) — complete events on the ``device ops`` track, laid
  sequentially from the step's begin stamp.  Placement within the step is
  synthetic (the parsed trace keeps durations, not cross-stream clocks);
  durations are real.

Everything is fail-soft: steps with no flight anchor are skipped, an
export error returns ``None`` — and nothing here ever issues a collective
(the module is rank-local-by-design; one trace file per process).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..logging import get_logger
from . import flightrec

logger = get_logger(__name__)

_HOST_TID = 1
_DEVICE_TID = 2
_FLIGHT_TID = 3

# in-call StepRecord phases in execution order; dataloader_wait_ms is laid
# before the begin anchor (it precedes the captured call)
_PHASE_ORDER = ("assembly_ms", "trace_ms", "compile_ms", "dispatch_ms")


def _metadata(pid: int, rank: int) -> list[dict]:
    return [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": f"rank {rank}"}},
        {"ph": "M", "pid": pid, "tid": _HOST_TID, "name": "thread_name",
         "args": {"name": "host phases"}},
        {"ph": "M", "pid": pid, "tid": _DEVICE_TID, "name": "thread_name",
         "args": {"name": "device ops"}},
        {"ph": "M", "pid": pid, "tid": _FLIGHT_TID, "name": "thread_name",
         "args": {"name": "flight events"}},
    ]


def build_trace(telemetry=None, recorder: Optional[flightrec.FlightRecorder] = None) -> dict:
    """Assemble the Trace Event Format document (µs timestamps) from the
    flight ring plus — when a telemetry hub is given — its host
    ``StepRecord`` timeline and sampled ``DeviceStepRecord`` stream."""
    rec = recorder if recorder is not None else flightrec.recorder()
    rank = flightrec.resolve_rank()
    pid = rank
    events: list[dict] = _metadata(pid, rank)

    flight = rec.snapshot()
    step_begin: dict[int, float] = {}
    step_end: dict[int, float] = {}
    for ev in flight:
        t_us = ev["t"] * 1e6
        if ev["kind"] == "step_begin" and "step" in ev:
            step_begin.setdefault(ev["step"], t_us)
        elif ev["kind"] == "step_end" and "step" in ev:
            step_end[ev["step"]] = t_us
        name = ev["kind"]
        if ev["kind"] == "collective":
            name = f"collective:{ev.get('op', '?')} #{ev.get('cseq', '?')}"
        args = {k: v for k, v in ev.items() if k not in ("kind", "t")}
        events.append(
            {"ph": "i", "s": "t", "pid": pid, "tid": _FLIGHT_TID,
             "ts": t_us, "name": name, "args": args}
        )

    host_records = []
    device_records = []
    if telemetry is not None:
        try:
            host_records = [r.to_dict() for r in telemetry.timeline.records()]
            device_records = [d.to_dict() for d in telemetry.device_records]
        except Exception:
            host_records, device_records = [], []

    for record in host_records:
        step = record.get("step")
        begin = step_begin.get(step)
        if begin is None:
            continue  # no flight anchor (recorder disabled mid-run): skip
        wait_ms = record.get("dataloader_wait_ms") or 0.0
        if wait_ms > 0:
            events.append(
                {"ph": "X", "pid": pid, "tid": _HOST_TID,
                 "ts": begin - wait_ms * 1e3, "dur": wait_ms * 1e3,
                 "name": f"step {step}: dataloader_wait", "cat": "host",
                 "args": {"step": step}}
            )
        cursor = begin
        for phase in _PHASE_ORDER:
            ms = record.get(phase) or 0.0
            if ms <= 0:
                continue
            events.append(
                {"ph": "X", "pid": pid, "tid": _HOST_TID, "ts": cursor,
                 "dur": ms * 1e3,
                 "name": f"step {step}: {phase[:-3]}", "cat": "host",
                 "args": {"step": step, "key": record.get("key"),
                          "built": record.get("built")}}
            )
            cursor += ms * 1e3

    for record in device_records:
        step = record.get("step")
        begin = step_begin.get(step)
        if begin is None:
            continue
        cursor = begin
        for name, ms in record.get("top_ops") or []:
            if not isinstance(ms, (int, float)) or ms <= 0:
                continue
            events.append(
                {"ph": "X", "pid": pid, "tid": _DEVICE_TID, "ts": cursor,
                 "dur": ms * 1e3, "name": str(name), "cat": "device",
                 "args": {"step": step, "ms": ms}}
            )
            cursor += ms * 1e3

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "accelerate_tpu.telemetry.trace_export",
            "rank": rank,
            "collective_seq": rec.collective_seq,
        },
    }


def export_chrome_trace(path: str, telemetry=None,
                        recorder: Optional[flightrec.FlightRecorder] = None
                        ) -> Optional[str]:
    """Write the joined trace JSON; returns the path, or ``None`` on any
    failure (export is observability — it must never crash the run)."""
    try:
        doc = build_trace(telemetry=telemetry, recorder=recorder)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return path
    except Exception as exc:
        logger.warning("chrome trace export to %r failed: %s", path, exc)
        return None


def validate_trace(doc) -> list[str]:
    """Structural well-formedness of a Trace Event Format document; ``[]``
    when valid.  The smoke (``tools/telemetry_smoke.py``) additionally
    asserts the three tracks carry events for the same steps."""
    errors: list[str] = []
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        return ["document is not an object with a traceEvents list"]
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            errors.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "I", "M", "B", "E", "C"):
            errors.append(f"event {i}: unknown ph {ph!r}")
            continue
        if "name" not in ev:
            errors.append(f"event {i}: no name")
        if ph in ("X", "i", "I") and not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"event {i}: ph={ph} without numeric ts")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            errors.append(f"event {i}: complete event without numeric dur")
    return errors
