"""collective-divergence: a collective op reachable only under rank-divergent
control flow.

The mesh's lockstep contract (docs/elastic.md): every rank issues the same
collective sequence, in the same order, or the mesh hangs.  This rule runs
the rank-divergence taint engine (``analysis/taint.py``) over each function
— seeded with the whole-program facts from ``ctx.divergent_aliases``
(functions proven to RETURN rank-divergent state) and
``ctx.collective_aliases`` (functions that transitively ISSUE a collective)
— and flags three shapes:

* **branch mismatch** — sibling branches of a rank-divergent conditional
  issue different collective sequences (including the degenerate and most
  common case: a collective on one side, nothing on the other — only the
  ranks taking that side enter it);
* **early exit** — a ``return``/``raise`` on a rank-divergent branch while
  a collective still follows in the function: the exiting ranks never reach
  it, the remaining ranks block in it forever;
* **divergent loop** — a collective inside a loop whose condition (or
  iterable) is rank-divergent: trip counts differ per rank, so the
  collective sequence does too.

The sanctioned fix shapes the rule recognizes (no suppression needed):
deriving the guard from an all-ranks merge (``gather_object`` /
``agree_*`` kill taint), and conjoining the branch with a single-process
world-size test (``not _multi_process()``, ``num_processes == 1``) — the
PR-13 serving-signal gate — which makes the branch unreachable on any
multi-process run.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Rule
from ..taint import (
    FunctionTaint,
    collective_sink,
    rank_local_by_design,
    single_process_conjunct,
)

_NESTED_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class CollectiveDivergence(Rule):
    id = "collective-divergence"
    kind = "reachability"
    description = (
        "collective op (gather/broadcast/barrier/load_state/fleet resize) "
        "guarded by rank-divergent state — only some ranks enter it and "
        "the mesh deadlocks"
    )
    fix_hint = (
        "derive the guard from an all-ranks merge (gather_object + agree_*) "
        "so every rank sees the same value, or gate the branch single-"
        "process (num_processes == 1 / not _multi_process())"
    )

    def check(self, module, ctx) -> list[Finding]:
        if rank_local_by_design(module.rel_path):
            # the postmortem-writer exemption (taint.RANK_LOCAL_MODULE_
            # SUFFIXES): rank identity / wall clock / fs probes here are the
            # point, so the divergence scan is waived — and the INVERTED
            # contract is enforced instead: a module that may run while the
            # mesh is deadlocked must never bear a collective at all.
            return self._check_rank_local_contract(module)
        findings: list[Finding] = []
        div_map = ctx.divergent_aliases.get(module.rel_path, {})
        coll_map = ctx.collective_aliases.get(module.rel_path, {})
        for info in module.callgraph.functions.values():
            self_prefix = (
                info.qualname.rsplit(".", 1)[0]
                if "." in info.qualname
                else None
            )
            taint = FunctionTaint(
                module, info.node, known=div_map, self_prefix=self_prefix
            )
            seen: set[tuple[int, str]] = set()

            def fire(node, kind, message):
                key = (node.lineno, kind)
                if key in seen:
                    return
                seen.add(key)
                findings.append(
                    Finding(
                        self.id,
                        module.rel_path,
                        node.lineno,
                        node.col_offset,
                        message,
                        symbol=info.qualname,
                    )
                )

            self._scan(
                info.node.body, [], module, taint, coll_map, fire
            )
        return findings

    def _check_rank_local_contract(self, module) -> list[Finding]:
        """The no-collective contract for rank-local-by-design modules: every
        collective sink anywhere in the module (function bodies AND module
        level) is a finding, unconditionally — divergence analysis does not
        apply because the module must not collectivize at all."""
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            tok = collective_sink(node, module)
            if tok is None:
                continue
            findings.append(
                Finding(
                    self.id,
                    module.rel_path,
                    node.lineno,
                    node.col_offset,
                    f"collective ({tok}) in a rank-local-by-design module: "
                    "the postmortem/watchdog path may run while the mesh is "
                    "deadlocked — coordinating over the stalled mesh hangs "
                    "the postmortem too.  Move the collective out of this "
                    "module; the rank-local exemption is conditional on "
                    "bearing none",
                )
            )
        return findings

    # -- token collection ----------------------------------------------------
    def _call_token(self, call, module, taint, coll_map):
        """Collective token for one Call: a direct sink, or a call into a
        function the program graph proved collective-bearing."""
        tok = collective_sink(call, module)
        if tok is not None:
            return tok
        for cand in taint.callee_names(call.func):
            if cand in coll_map:
                return cand
        return None

    def _expr_tokens(self, node, module, taint, coll_map):
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                tok = self._call_token(sub, module, taint, coll_map)
                if tok is not None:
                    out.append(tok)
        return out

    def _tokens(self, stmts, module, taint, coll_map):
        """Collective tokens issued by a statement list, skipping nested
        defs (their own call-graph nodes) and single-process-guarded
        branches (unreachable on a multi-process run)."""
        out = []
        for stmt in stmts:
            if isinstance(stmt, _NESTED_DEFS):
                continue
            if isinstance(stmt, ast.If) and single_process_conjunct(stmt.test):
                out += self._expr_tokens(stmt.test, module, taint, coll_map)
                out += self._tokens(stmt.orelse, module, taint, coll_map)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    out += self._tokens([child], module, taint, coll_map)
                elif isinstance(child, ast.ExceptHandler):
                    if child.type is not None:
                        out += self._expr_tokens(
                            child.type, module, taint, coll_map
                        )
                    out += self._tokens(child.body, module, taint, coll_map)
                elif isinstance(child, ast.withitem):
                    out += self._expr_tokens(
                        child.context_expr, module, taint, coll_map
                    )
                elif hasattr(ast, "match_case") and isinstance(
                    child, ast.match_case
                ):
                    out += self._tokens(child.body, module, taint, coll_map)
                elif isinstance(child, ast.expr):
                    out += self._expr_tokens(child, module, taint, coll_map)
        return out

    def _exits(self, stmts):
        """Top-to-bottom ``return``/``raise`` statements inside a branch (any
        nesting short of nested defs) — the exits that abandon the rest of
        the function for the ranks that took this branch."""
        out = []
        for stmt in stmts:
            if isinstance(stmt, _NESTED_DEFS):
                continue
            if isinstance(stmt, (ast.Return, ast.Raise)):
                out.append(stmt)
                continue
            if isinstance(stmt, ast.If) and single_process_conjunct(stmt.test):
                out += self._exits(stmt.orelse)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    out += self._exits([child])
                elif isinstance(child, ast.ExceptHandler):
                    out += self._exits(child.body)
                elif hasattr(ast, "match_case") and isinstance(
                    child, ast.match_case
                ):
                    out += self._exits(child.body)
        return out

    # -- the statement scan ----------------------------------------------------
    def _scan(self, stmts, tail, module, taint, coll_map, fire):
        """``tail`` carries the collective tokens that follow the current
        block at every enclosing level — what an early exit would skip."""
        for idx, stmt in enumerate(stmts):
            if isinstance(stmt, _NESTED_DEFS):
                continue
            after = (
                self._tokens(stmts[idx + 1:], module, taint, coll_map) + tail
            )
            if isinstance(stmt, ast.If):
                if single_process_conjunct(stmt.test):
                    # the branch never executes multi-process: nothing inside
                    # it can diverge a mesh (the sanctioned PR-13 gate)
                    self._scan(
                        stmt.orelse, after, module, taint, coll_map, fire
                    )
                    continue
                if taint.expr_tainted(stmt.test):
                    desc = taint.describe(stmt.test)
                    body_toks = self._tokens(
                        stmt.body, module, taint, coll_map
                    )
                    else_toks = self._tokens(
                        stmt.orelse, module, taint, coll_map
                    )
                    if sorted(body_toks) != sorted(else_toks):
                        fire(
                            stmt,
                            "branch",
                            "collective sequence diverges across ranks: "
                            f"branch on rank-divergent {desc} issues "
                            f"[{', '.join(sorted(body_toks)) or 'nothing'}] vs "
                            f"[{', '.join(sorted(else_toks)) or 'nothing'}] "
                            "on the sibling path — only some ranks enter, "
                            "the mesh deadlocks",
                        )
                    if after:
                        for branch in (stmt.body, stmt.orelse):
                            for exit_stmt in self._exits(branch):
                                word = (
                                    "return"
                                    if isinstance(exit_stmt, ast.Return)
                                    else "raise"
                                )
                                fire(
                                    exit_stmt,
                                    "exit",
                                    f"early {word} on a rank-divergent "
                                    f"branch ({desc}) skips the later "
                                    f"collective ({after[0]}) — exiting "
                                    "ranks never reach it, the rest block "
                                    "in it forever",
                                )
                self._scan(stmt.body, after, module, taint, coll_map, fire)
                self._scan(stmt.orelse, after, module, taint, coll_map, fire)
            elif isinstance(stmt, ast.While):
                if not single_process_conjunct(stmt.test) and taint.expr_tainted(
                    stmt.test
                ):
                    toks = self._tokens(stmt.body, module, taint, coll_map)
                    if toks:
                        fire(
                            stmt,
                            "loop",
                            f"collective ({toks[0]}) inside a loop whose "
                            "condition is rank-divergent "
                            f"({taint.describe(stmt.test)}) — trip counts "
                            "differ per rank, so the collective sequence "
                            "does too",
                        )
                self._scan(stmt.body, after, module, taint, coll_map, fire)
                self._scan(stmt.orelse, after, module, taint, coll_map, fire)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if taint.expr_tainted(stmt.iter):
                    toks = self._tokens(stmt.body, module, taint, coll_map)
                    if toks:
                        fire(
                            stmt,
                            "loop",
                            f"collective ({toks[0]}) inside a loop over a "
                            "rank-divergent iterable "
                            f"({taint.describe(stmt.iter)}) — trip counts "
                            "differ per rank, so the collective sequence "
                            "does too",
                        )
                self._scan(stmt.body, after, module, taint, coll_map, fire)
                self._scan(stmt.orelse, after, module, taint, coll_map, fire)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._scan(stmt.body, after, module, taint, coll_map, fire)
            elif isinstance(stmt, ast.Try) or (
                hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)
            ):
                self._scan(stmt.body, after, module, taint, coll_map, fire)
                for h in stmt.handlers:
                    self._scan(h.body, after, module, taint, coll_map, fire)
                self._scan(stmt.orelse, after, module, taint, coll_map, fire)
                self._scan(
                    stmt.finalbody, after, module, taint, coll_map, fire
                )
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    self._scan(case.body, after, module, taint, coll_map, fire)
