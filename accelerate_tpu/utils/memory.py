"""Memory utilities — OOM-retry and device-memory bookkeeping.

Counterpart of ``/root/reference/src/accelerate/utils/memory.py`` (200 LoC):
``find_executable_batch_size`` (memory.py:120) halves the batch size on OOM
and retries; ``release_memory`` (memory.py:70) drops references and clears
caches; ``clear_device_cache`` (memory.py:43).

TPU-native differences: XLA raises ``XlaRuntimeError`` with a
RESOURCE_EXHAUSTED status instead of torch's ``cuda OOM`` RuntimeError, and
"clearing the cache" means deleting live buffers + dropping jit compilation
caches — there is no CUDA caching allocator. Live-array accounting comes from
``jax.live_arrays()`` and per-device memory stats from
``Device.memory_stats()`` (PJRT).
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable, Optional

import jax


def should_reduce_batch_size(exception: Exception) -> bool:
    """True when ``exception`` is an out-of-memory condition worth retrying
    at a smaller batch size (reference memory.py:95 checks CUDA/CPU/XPU OOM
    strings; here: XLA RESOURCE_EXHAUSTED / allocation failures)."""
    statuses = (
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "OOM",
        "Attempting to allocate",
        "exceeds the maximum",
    )
    msg = str(exception)
    if isinstance(exception, MemoryError):
        return True
    return any(s in msg for s in statuses)


def clear_device_cache(garbage_collection: bool = False) -> None:
    """Free what can be freed: python garbage, then XLA compilation caches.

    Reference clear_device_cache (memory.py:43) calls per-backend
    ``empty_cache``; PJRT has no caching allocator, so the analog is GC (drops
    unreferenced device buffers immediately) plus clearing jit caches so
    stale executables don't pin donated buffers.
    """
    if garbage_collection:
        gc.collect()
    try:
        jax.clear_caches()
    except Exception:  # pragma: no cover - defensive, clear_caches is stable
        pass


def release_memory(*objects):
    """Set references to None and clear the cache (reference memory.py:70).

    Usage: ``a, b = release_memory(a, b)``.
    """
    if not isinstance(objects, list):
        objects = list(objects)
    for i in range(len(objects)):
        objects[i] = None
    gc.collect()
    clear_device_cache()
    return objects


def get_device_memory_stats(device: Optional[jax.Device] = None) -> dict:
    """Per-device memory stats from PJRT (bytes_in_use, peak_bytes_in_use,
    bytes_limit where the platform reports them)."""
    device = device or jax.devices()[0]
    stats = {}
    try:
        stats = dict(device.memory_stats() or {})
    except Exception:
        pass
    return stats


def opt_state_bytes_per_replica(optimizer) -> int:
    """Bytes of optimizer state (optax moments + fp32 masters) resident on
    ONE device — the number ZeRO-1/FSDP state sharding shrinks by ~1/dp.

    Accepts an ``optim.Optimizer`` or an ``AcceleratedOptimizer`` wrapper.
    Per-device residency is the first addressable shard's bytes per leaf
    (replicated leaves report full size, dp/fsdp-sharded leaves 1/axis);
    0-d leaves (step counters, hyperparams) are skipped as noise.
    """
    inner = getattr(optimizer, "optimizer", optimizer)
    leaves = list(jax.tree_util.tree_leaves(inner.opt_state))
    leaves += [m for m in getattr(inner, "master_params", []) if m is not None]
    total = 0
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and leaf.ndim >= 1:
            # addressable_shards works on multi-host (non-fully-addressable)
            # global arrays too — each host sees its own shards, and shard 0
            # is one replica's residency either way
            shards = leaf.addressable_shards
            if shards:
                total += shards[0].data.nbytes
    return total


def find_executable_batch_size(
    function: Optional[Callable] = None,
    starting_batch_size: int = 128,
    reduce_batch_size_fn: Optional[Callable[[int], int]] = None,
):
    """Decorator: retry ``function(batch_size, *a, **kw)`` halving
    ``batch_size`` whenever an OOM is raised, until it succeeds or reaches 0.

    Mirrors reference find_executable_batch_size (memory.py:120): the
    decorated function MUST take ``batch_size`` as its first argument; each
    retry clears device caches first. On TPU an OOM surfaces at compile- or
    run-time as RESOURCE_EXHAUSTED — both are caught.
    """
    if function is None:
        return functools.partial(
            find_executable_batch_size,
            starting_batch_size=starting_batch_size,
            reduce_batch_size_fn=reduce_batch_size_fn,
        )

    reduce_fn = reduce_batch_size_fn or (lambda b: b // 2)
    batch_size_box = [starting_batch_size]

    @functools.wraps(function)
    def decorator(*args, **kwargs):
        batch_size_box[0] = starting_batch_size
        params = list(inspect.signature(function).parameters.keys())
        if len(params) < 1 or params[0] != "batch_size":
            arg_str = ", ".join(params)
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the "
                f"first argument when called.\nRemove this as the decorator "
                f"already does so: `{function.__name__}({arg_str})`"
            )
        while True:
            if batch_size_box[0] == 0:
                raise RuntimeError(
                    "No executable batch size found, reached zero."
                )
            try:
                return function(batch_size_box[0], *args, **kwargs)
            except Exception as e:
                if should_reduce_batch_size(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size_box[0] = reduce_fn(batch_size_box[0])
                else:
                    raise

    return decorator
