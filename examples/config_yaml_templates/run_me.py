"""Print how the launched environment resolved (reference
/root/reference/examples/config_yaml_templates/run_me.py:1): every template
in this folder can be driven through this script to see the mesh, precision,
and process topology it produces."""

import os
import sys

sys.path.append(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from accelerate_tpu import Accelerator  # noqa: E402

accelerator = Accelerator()
accelerator.print(
    f"Accelerator state from the current environment:\n{accelerator.state}"
)
if accelerator.fp8_recipe_handler is not None:
    accelerator.print(f"FP8 config:\n{accelerator.fp8_recipe_handler}")
accelerator.end_training()
