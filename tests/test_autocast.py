"""autocast(): locally-fp32 regions inside a bf16 model (reference
accelerator.py:3587 ``torch.autocast`` disable idiom)."""

import jax.numpy as jnp

import accelerate_tpu.nn as nn
from accelerate_tpu import Accelerator
from accelerate_tpu.nn.amp import autocast_dtype, autocast_region
from accelerate_tpu.utils.dataclasses import AutocastKwargs


def test_region_state_nests_and_restores():
    assert autocast_dtype() is None
    with autocast_region(jnp.float32):
        assert autocast_dtype() == jnp.float32
        with autocast_region(jnp.bfloat16):
            assert autocast_dtype() == jnp.bfloat16
        assert autocast_dtype() == jnp.float32
    assert autocast_dtype() is None


def test_disabled_autocast_upcasts_linear_to_fp32():
    acc = Accelerator(mixed_precision="bf16")
    model = nn.Linear(8, 4)
    model = acc.prepare(model)
    assert model.weight.dtype == jnp.bfloat16
    x = nn.Tensor(jnp.ones((2, 8), jnp.bfloat16))

    out_ambient = model(x)
    assert out_ambient.dtype == jnp.bfloat16

    with acc.autocast(autocast_handler=AutocastKwargs(enabled=False)):
        out_fp32 = model(x)
    assert out_fp32.dtype == jnp.float32

    # handler can also be installed at construction time
    acc2 = Accelerator(
        mixed_precision="bf16", kwargs_handlers=[AutocastKwargs(enabled=False)]
    )
    model2 = acc2.prepare(nn.Linear(8, 4))
    with acc2.autocast():
        out2 = model2(x)
    assert out2.dtype == jnp.float32
    Accelerator._reset_state()


def test_cross_entropy_upcasts_in_fp32_region():
    logits = nn.Tensor(jnp.asarray([[2.0, 0.0], [0.0, 2.0]], jnp.bfloat16))
    labels = jnp.asarray([0, 1])
    with autocast_region(jnp.float32):
        loss = nn.F.cross_entropy(logits, labels)
    assert loss.dtype == jnp.float32
