#!/usr/bin/env python3
"""bench_compare: the bench-regression gate (ROADMAP autotune carried item).

The repo keeps a ``BENCH_r*.json`` trajectory, but until now nothing FAILED
when ``step_ms`` regressed — a slow hot path could ride a green CI forever.
This tool diffs the newest artifact's primary ``step_ms`` against the
previous round and exits non-zero past a threshold:

* only **CPU-geometry rows are comparable to each other** (the default
  gate): a TPU row against a CPU row is a platform change, not a
  regression, so mixed-platform pairs are reported and skipped unless both
  artifacts ran on the same platform;
* the threshold is ``$BENCH_REGRESSION_PCT`` (default 10): CI noise on the
  CPU geometry sits well under that (r02→r05 moved within ±7%), so a trip
  means a real hot-path change;
* artifacts wrap the parsed row under ``{"parsed": {...}}`` (the driver
  format) or carry the fields at top level (a direct ``bench.py`` dump) —
  both are read.

Usage::

    python tools/bench_compare.py                  # newest two BENCH_r*.json
    python tools/bench_compare.py --files A B      # explicit pair (A=older)
    python tools/bench_compare.py --pct 5          # tighter threshold

``make bench-gate`` chains this into CI (Makefile); tests/test_kernels.py
pins the injected-regression trip and the current-trajectory pass.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def parsed_row(path: str) -> dict:
    """The primary-result dict of one artifact (driver-wrapped or direct)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        return {}
    inner = data.get("parsed")
    return inner if isinstance(inner, dict) else data


def trajectory(bench_dir: str) -> list[str]:
    """``BENCH_r*.json`` paths in round order (live/partial variants are
    not rounds and do not gate)."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        match = _ROUND_RE.search(os.path.basename(path))
        if match:
            rounds.append((int(match.group(1)), path))
    return [path for _, path in sorted(rounds)]


def compare(prev_path: str, new_path: str, pct: float) -> tuple[int, str]:
    """(exit code, human verdict) for one artifact pair."""
    prev, new = parsed_row(prev_path), parsed_row(new_path)
    prev_ms, new_ms = prev.get("step_ms"), new.get("step_ms")
    if not isinstance(prev_ms, (int, float)) or not isinstance(new_ms, (int, float)):
        return 0, (
            f"skip: no comparable step_ms ({os.path.basename(prev_path)}="
            f"{prev_ms!r}, {os.path.basename(new_path)}={new_ms!r})"
        )
    prev_plat, new_plat = prev.get("platform"), new.get("platform")
    if prev_plat != new_plat:
        return 0, (
            f"skip: platform moved {prev_plat!r} -> {new_plat!r} — rows are "
            "not comparable (the gate compares same-platform, CPU-geometry "
            "trajectories)"
        )
    delta_pct = (new_ms - prev_ms) / prev_ms * 100.0
    line = (
        f"{os.path.basename(prev_path)} step_ms={prev_ms} -> "
        f"{os.path.basename(new_path)} step_ms={new_ms} "
        f"({delta_pct:+.1f}%, threshold +{pct:.0f}%)"
    )
    if delta_pct > pct:
        return 1, f"REGRESSION: {line}"
    return 0, f"ok: {line}"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--files", nargs=2, metavar=("PREV", "NEW"),
        help="explicit artifact pair (default: newest two BENCH_r*.json)",
    )
    parser.add_argument(
        "--bench-dir", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json trajectory",
    )
    parser.add_argument(
        "--pct", type=float,
        default=float(os.environ.get("BENCH_REGRESSION_PCT", 10)),
        help="fail when step_ms grows more than this percent (default "
        "$BENCH_REGRESSION_PCT or 10)",
    )
    args = parser.parse_args(argv)
    if args.files:
        prev_path, new_path = args.files
    else:
        rounds = trajectory(args.bench_dir)
        if len(rounds) < 2:
            print(f"bench-gate: skip — fewer than two rounds in {args.bench_dir}")
            return 0
        prev_path, new_path = rounds[-2], rounds[-1]
    code, verdict = compare(prev_path, new_path, args.pct)
    print(f"bench-gate: {verdict}")
    return code


if __name__ == "__main__":
    sys.exit(main())
