"""OPT-family decoder tests: HF parity, decode, sharded inference.

The family is BASELINE.json config 5 ("OPT-6.7B device_map='auto' sharded
inference"; reference benchmarks/big_model_inference/README.md:31-37).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp

from accelerate_tpu.models import OPTConfig, OPTForCausalLM


def _tiny_hf_pair(seed=0):
    from transformers import OPTConfig as HFConfig, OPTForCausalLM as HFOPT

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(seed)
    hf = HFOPT(
        HFConfig(
            vocab_size=1024, hidden_size=128, ffn_dim=256, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=256,
            do_layer_norm_before=True, word_embed_proj_dim=128,
            activation_function="relu", dropout=0.0, attention_dropout=0.0,
        )
    ).eval()
    return hf, convert_torch_module(hf)


@pytest.fixture(scope="module")
def hf_pair():
    return _tiny_hf_pair()


def test_forward_parity_vs_transformers(hf_pair):
    hf, ours = hf_pair
    ids = np.random.default_rng(0).integers(0, 1024, (2, 16), dtype=np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids, jnp.int32))["logits"].data)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_greedy_generate_matches_full_forward(hf_pair):
    _, ours = hf_pair
    ids = np.random.default_rng(1).integers(0, 1024, (2, 7), dtype=np.int32)
    want = jnp.asarray(ids, jnp.int32)
    for _ in range(5):
        logits = ours(want)["logits"].data
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want = jnp.concatenate([want, nxt[:, None]], axis=1)
    got = ours.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_post_norm_geometry_rejected():
    with pytest.raises(NotImplementedError, match="350m"):
        OPTConfig(do_layer_norm_before=False)


def test_shard_for_inference_generate():
    """config-5 shape: GSPMD-sharded OPT generation over the mesh."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.big_modeling import shard_for_inference

    Accelerator._reset_state()
    import accelerate_tpu.nn as nn

    nn.manual_seed(0)
    model = OPTForCausalLM(OPTConfig.tiny())
    model = shard_for_inference(model)
    model.eval()
    ids = np.zeros((1, 8), dtype=np.int32)
    out = model.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 12)


def test_unsupported_config_fields_rejected():
    from accelerate_tpu.utils.hf import opt_config_from_hf

    with pytest.raises(NotImplementedError, match="activation_function"):
        opt_config_from_hf({"activation_function": "gelu"})
    with pytest.raises(NotImplementedError, match="word_embed_proj_dim"):
        opt_config_from_hf({"hidden_size": 1024, "word_embed_proj_dim": 512})


def test_from_pretrained_roundtrip(tmp_path, hf_pair):
    hf, ours = hf_pair
    hf.save_pretrained(tmp_path / "opt")
    from accelerate_tpu.utils.hf import from_pretrained

    loaded = from_pretrained(str(tmp_path / "opt"))
    ids = np.random.default_rng(2).integers(0, 1024, (1, 12), dtype=np.int32)
    a = np.asarray(ours(jnp.asarray(ids))["logits"].data)
    b = np.asarray(loaded(jnp.asarray(ids))["logits"].data)
    np.testing.assert_allclose(a, b, atol=1e-6)
