"""Pipeline-parallel inference parity: the pipelined trunk must produce the
same logits as the plain unrolled model.

Counterpart of the reference's
``test_utils/scripts/external_deps/test_pippy.py:48-117`` (prepare_pippy on
bert/gpt2, output checked on the last stage).  TPU-native: instead of
torch.fx graph splitting, the trunk is a GPipe shard_map over the ``pp``
mesh axis (parallel/pipeline.py) packaged as
``models.PipelinedGPTLMHeadModel``; every rank holds the same global output
(GSPMD), so parity is checked everywhere rather than on the last stage only.
"""

from __future__ import annotations

import numpy as np

import accelerate_tpu.nn as nn
from accelerate_tpu import Accelerator, ParallelismConfig, set_seed
from accelerate_tpu.models import GPTConfig
from accelerate_tpu.models.gpt import PipelinedGPTLMHeadModel


def test_gpt2(pp_size: int = 2):
    import jax
    import jax.numpy as jnp

    set_seed(42)
    Accelerator._reset_state()
    n_dev = len(jax.devices())
    pp = pp_size if n_dev % pp_size == 0 and n_dev >= pp_size else 1

    nn.manual_seed(7)
    piped = PipelinedGPTLMHeadModel(GPTConfig.tiny(), num_microbatches=2)
    rows = max(4, 2 * n_dev)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 1024, (rows, 32)), jnp.int32
    )
    # reference logits BEFORE preparation: with no AcceleratorState mesh the
    # trunk takes the degenerate sequential-scan path — the "original model"
    # in the reference's split-vs-original contract
    with nn.no_grad():
        want = np.asarray(piped(ids)["logits"], np.float32)

    acc = Accelerator(parallelism_config=ParallelismConfig(pp_size=pp))
    piped = acc.prepare(piped)
    from accelerate_tpu.data_loader import batch_to_global_array

    gids = batch_to_global_array(ids, mesh=acc.mesh)
    with nn.no_grad():
        got = np.asarray(piped(gids)["logits"], np.float32)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
    print(
        f"rank{acc.process_index}: pipelined gpt2 parity ok "
        f"(pp={pp}, microbatches=2, out {got.shape})"
    )


def main():
    test_gpt2()


if __name__ == "__main__":
    main()
