"""Config knobs wired in round 3: ProfileKwargs tracer options, FSDP
param_dtype/reduce_dtype (MixedPrecisionPolicy analog), fp8
amax_compute_algo. Each was previously declared-but-ignored."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.utils.dataclasses import (
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    ProfileKwargs,
)


def _fresh():
    Accelerator._reset_state()
    nn.manual_seed(0)


def test_profile_writes_trace_and_memory(tmp_path):
    _fresh()
    acc = Accelerator()
    seen = []
    handler = ProfileKwargs(
        output_trace_dir=str(tmp_path),
        profile_memory=True,
        with_flops=True,
        on_trace_ready=seen.append,
    )
    with acc.profile(handler):
        jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert seen == [str(tmp_path)]
    assert os.path.exists(tmp_path / "memory.prof")
    # the trace itself lands under plugins/profile/<ts>/
    assert any(p.name.startswith("plugins") for p in tmp_path.iterdir())


def test_fsdp_param_dtype_overrides_global_precision():
    _fresh()
    acc = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(param_dtype="bf16")
    )
    model = acc.prepare_model(nn.Linear(4, 4))
    assert all(p.data.dtype == jnp.bfloat16 for p in model.parameters())


def test_fsdp_reduce_dtype_compresses_synced_grads():
    _fresh()
    acc = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(reduce_dtype="bf16")
    )
    model = nn.Linear(8, 4)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    acc.backward(model(nn.Tensor(jnp.ones((2, 8), jnp.float32))).sum())
    assert all(p.grad.dtype == jnp.bfloat16 for p in model.parameters())


def test_fsdp_bad_dtype_string_raises_at_construction():
    with pytest.raises(ValueError, match="reduce_dtype"):
        FullyShardedDataParallelPlugin(reduce_dtype="int8")
    with pytest.raises(ValueError, match="param_dtype"):
        FullyShardedDataParallelPlugin(param_dtype="bf-16")


def test_fp8_survives_param_dtype():
    """param_dtype must tune the residual dtype under fp8, not disable the
    fp8 linear swap (review finding)."""
    from accelerate_tpu.utils.fp8 import FP8Linear

    _fresh()
    acc = Accelerator(
        mixed_precision="fp8",
        fsdp_plugin=FullyShardedDataParallelPlugin(param_dtype="bf16"),
    )
    # 3 Linears: first/last stay precision-critical, the middle one converts
    model = acc.prepare_model(
        nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8), nn.Linear(8, 4))
    )
    assert any(isinstance(m, FP8Linear) for m in model.modules())


def test_fp8_amax_compute_algo():
    from accelerate_tpu.utils.fp8 import FP8Linear

    _fresh()
    x = nn.Tensor(jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32))
    outs = {}
    for algo in ("max", "most_recent"):
        nn.manual_seed(0)
        lin = FP8Linear(8, 4, recipe=FP8RecipeKwargs(amax_compute_algo=algo))
        lin.set_delayed(True)
        lin(x)  # seeds the history
        outs[algo] = np.asarray(lin(x).data)
    # both run; with a single-step history they agree numerically
    for v in outs.values():
        assert np.isfinite(v).all()
    nn.manual_seed(0)
    bad = FP8Linear(8, 4, recipe=FP8RecipeKwargs(amax_compute_algo="median"))
    bad.set_delayed(True)
    with pytest.raises(ValueError, match="amax_compute_algo"):
        bad(x)


def test_plugin_activation_checkpointing_engages_remat():
    """FSDP activation_checkpointing (also set by the launcher's
    FSDP_ACTIVATION_CHECKPOINTING env) must wire through to per-layer
    jax.checkpoint in maybe_remat — previously a dormant accepted knob."""
    from accelerate_tpu.models.gpt import maybe_remat
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    def fn(x):
        return x * 2

    _fresh()
    assert maybe_remat(fn) is fn  # no state, no env: untouched
    Accelerator(fsdp_plugin=FullyShardedDataParallelPlugin(activation_checkpointing=True))
    wrapped = maybe_remat(fn)
    assert wrapped is not fn, "plugin flag did not engage jax.checkpoint"
    np.testing.assert_allclose(
        np.asarray(wrapped(jnp.arange(4.0))), np.asarray(fn(jnp.arange(4.0)))
    )
    _fresh()
    assert maybe_remat(fn) is fn
