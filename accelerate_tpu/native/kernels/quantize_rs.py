"""Fused quantize+reduce-scatter (docs/kernels.md §quantize-rs).

``parallel/compress.py``'s reference wire is four separate XLA ops with an
HBM round-trip between each: per-block ``amax`` → scale divide →
round/clip/narrow → widen-by-scales.  This module collapses scale compute,
rounding and widening into ONE Pallas kernel region, so on TPU the whole
quantize→dequantize ride happens in VMEM next to the shard boundary the
payload crosses ("scale+round ride the RDMA hops" — the EQuARX move,
PAPERS.md #3), and the StableHLO the captured program commits to keeps the
narrow (int8 / f8E4M3FN) payload at the boundary instead of a widened fp32
intermediate (asserted by ``inspect.check_quantize_rs``).

Numerics contract: the kernel body runs the reference's EXACT op sequence
(``compress.quantize`` then ``compress.dequantize``), so under jit the wire
is **bitwise-identical** to the reference path — which makes the
error-feedback residual evolution bitwise too (the residual math stays
outside the kernel, shared with the reference).  Verified on CPU through
interpreter mode in tests/test_kernels.py.

The stochastic-rounding wire (``stochastic_quantize_dequantize`` /
``zero2_stochastic_wire``) reopens the ZeRO-2 first scatter: PR 6 kept that
scatter layout-only because deterministically re-rounding a running fp32
accumulation every micro-step compounds bias ``num_steps`` times.
Stochastic rounding (``floor(y + u)``, ``u ~ U[0,1)``) is unbiased —
``E[wire] == sum`` at every micro-step — so the accumulated gradient can
cross the dp boundary narrow during accumulation without systematic drift
(int8 wire only; fp8 stays deterministic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...parallel.compress import _qmax, _to_layout, dequantize, quantize

__all__ = [
    "fused_quantize_dequantize",
    "fused_reduce_scatter",
    "stochastic_quantize_dequantize",
    "zero2_stochastic_wire",
]


def _qdq_kernel(x_ref, o_ref, *, axis: int, wire_dtype):
    """One region: per-block amax → scale → round/clip → narrow → widen —
    by calling the reference's own ``compress.quantize``/``dequantize`` on
    the loaded value (they are pure jnp, so they trace into the kernel
    body unchanged), which is what makes the fused wire bitwise-identical
    BY CONSTRUCTION: a future edit to the reference math cannot silently
    diverge the kernel."""
    payload, scales = quantize(x_ref[:], axis, wire_dtype)
    o_ref[:] = dequantize(payload, scales)


def fused_quantize_dequantize(x, axis: int, wire_dtype, *, interpret: bool = True):
    """``x`` (fp32) → the wire value (fp32, same shape): what the far side
    of the quantized reduce-scatter reconstructs, computed in one kernel."""
    kernel = functools.partial(_qdq_kernel, axis=axis, wire_dtype=wire_dtype)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
        interpret=interpret,
    )(x)


def fused_reduce_scatter(x32, sharding, axis: int, err, policy, *,
                         interpret: bool = True):
    """Drop-in for :meth:`CompressionPolicy.reduce_scatter` with the wire
    computed by the fused kernel.  Returns ``(g_used, err_new)`` with the
    identical contract — and identical bits: the residual update
    (``used = wire + err``, ``err_new = truth - wire``) is the reference's
    own math on a bitwise-equal wire."""
    wire = fused_quantize_dequantize(
        x32, axis, policy.wire_dtype, interpret=interpret
    )
    wire = _to_layout(wire, sharding)
    if err is None:
        return wire, None
    used = wire + err
    truth = _to_layout(x32, sharding)
    return used, truth - wire


# ---------------------------------------------------------------------------
# stochastic-rounding wire (the ZeRO-2 first scatter)
# ---------------------------------------------------------------------------
def _sr_kernel(x_ref, u_ref, o_ref, *, axis: int, qmax: float):
    """Same fused region with ``floor(y + u)`` in place of ``round(y)`` —
    unbiased over ``u ~ U[0,1)``: int8 wire for the mid-accumulation
    scatter."""
    x = x_ref[:]
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    amax = jnp.max(jnp.abs(x), axis=reduce_axes, keepdims=True)
    scales = amax / qmax
    safe = jnp.where(scales > 0, scales, 1.0)
    y = x / safe
    payload = jnp.clip(jnp.floor(y + u_ref[:]), -qmax, qmax).astype(jnp.int8)
    o_ref[:] = payload.astype(jnp.float32) * scales


def stochastic_quantize_dequantize(x, axis: int, key, *, interpret: bool = True):
    """Stochastically-rounded int8 wire value of ``x``: deterministic for a
    fixed ``key`` (replay-stable under capture — the key threads through
    the captured RNG state), unbiased across keys."""
    u = jax.random.uniform(key, x.shape, jnp.float32)
    kernel = functools.partial(_sr_kernel, axis=axis, qmax=_qmax(jnp.int8))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
        interpret=interpret,
    )(x, u)


def zero2_stochastic_wire(grad, sharding, axis: int, key, *,
                          interpret: bool = True):
    """The ZeRO-2 mid-accumulation scatter, narrow: stochastic int8 wire +
    the same layout constraint ``compress.shard_accumulation`` applies.

    PR 6's layout-only scatter refused to quantize here because
    deterministic rounding would bias the running sum ``num_steps`` times;
    the stochastic wire's per-micro-step re-round is unbiased
    (``E[wire] == sum``), which is what reopens the narrow first scatter
    (docs/kernels.md §stochastic wire; armed only when the kernel policy
    AND an int8 collective policy AND ZeRO-2 are all on)."""
    wire = stochastic_quantize_dequantize(grad, axis, key, interpret=interpret)
    return _to_layout(wire, sharding)
