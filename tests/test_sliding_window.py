"""Sliding-window (Mistral-style) attention: flash-kernel parity with the
reference band mask, gradients, tile skipping, and the Llama family knob.
Kernels run in interpret mode on CPU (same block schedule as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.ops.flash_attention as fa
from accelerate_tpu.ops.attention import sdpa_reference


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(fa, "_INTERPRET", True)


def _rand_qkv(b=1, h=2, s=256, d=64, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, (b, h, s, d), jnp.float32),
        jax.random.normal(kk, (b, h, s, d), jnp.float32),
        jax.random.normal(kv, (b, h, s, d), jnp.float32),
    )


def test_reference_band_mask_semantics():
    """Row i of the reference band softmax spans exactly (i-w, i]."""
    s, w = 8, 3
    q = jnp.zeros((1, 1, s, 4))
    k = jnp.zeros((1, 1, s, 4))
    v = jnp.eye(s)[None, None, :, :4]  # value j one-hot → probs readable
    out = sdpa_reference(q, k, v, is_causal=True, window=w)
    probs_row = np.asarray(out[0, 0])  # uniform over the band
    for i in range(s):
        lo = max(0, i - w + 1)
        width = i - lo + 1
        expect = np.zeros(4)
        for j in range(lo, min(i + 1, 4)):
            expect[j] = 1.0 / width
        np.testing.assert_allclose(probs_row[i][:4], expect[:4], atol=1e-6)


@pytest.mark.parametrize("window", [128, 256, 384])
def test_forward_matches_reference(window):
    q, k, v = _rand_qkv(s=512)
    out = fa.flash_attention(q, k, v, True, None, window)
    ref = sdpa_reference(q, k, v, is_causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_window_not_multiple_of_block():
    """Bands that cut through tiles (not block-aligned) still mask exactly."""
    q, k, v = _rand_qkv(s=256)
    out = fa.flash_attention(q, k, v, True, None, 200)
    ref = sdpa_reference(q, k, v, is_causal=True, window=200)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [64, 200, 256])
def test_narrowed_grid_multi_tile_parity(window):
    """128-tile grid at seq 512 → the narrowed k-grid path (window_tiles>0)
    runs with real clamped-duplicate visits; parity must hold exactly."""
    q, k, v = _rand_qkv(s=512)
    out = fa._flash_forward(
        q, k, v, q.shape[-1] ** -0.5, True, block_q=128, block_k=128,
        window=window,
    )
    ref = sdpa_reference(q, k, v, is_causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_cross_length_windowed_matches_reference():
    """sq != sk must NOT take the narrowed grid (clamped tiles would be
    mislabeled — review catch, reproduced): full-grid fallback stays exact.

    The kernel's cross-length causal convention is START-aligned global
    positions (q_pos = i, k_pos = j — the ring-hop contract), so compare
    against a start-aligned band reference, with window large enough that
    every q row keeps at least one visible key.
    """
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (1, 2, 512, 64), jnp.float32)
    k = jax.random.normal(kk, (1, 2, 256, 64), jnp.float32)
    v = jax.random.normal(kv, (1, 2, 256, 64), jnp.float32)
    window = 384  # > 511 - 255: no fully-masked q rows
    out = fa._flash_forward(
        q, k, v, 64 ** -0.5, True, block_q=128, block_k=128, window=window
    )
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * (
        64 ** -0.5
    )
    i = jnp.arange(512)[:, None]
    j = jnp.arange(256)[None, :]
    keep = (i >= j) & (i - j < window)
    logits = jnp.where(keep[None, None], logits, -0.7 * float(jnp.finfo(jnp.float32).max))
    ref = jnp.einsum(
        "bhqk,bhkd->bhqd", jax.nn.softmax(logits, axis=-1).astype(v.dtype), v
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_narrowed_grid_only_without_offsets():
    """Ring hops (traced offsets) must keep the full k-grid — offsets are
    invisible to the static index map."""
    q, k, v = _rand_qkv(s=256)
    # static zero offsets → narrowed; same call with traced offsets must
    # still be correct (falls back to full grid + predicate)
    out = fa._flash_forward(
        q, k, v, q.shape[-1] ** -0.5, True, block_q=128, block_k=128,
        window=128, q_offset=jnp.asarray(0), k_offset=jnp.asarray(0),
    )
    ref = sdpa_reference(q, k, v, is_causal=True, window=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_decode_matches_forward_for_windowed_config():
    """Windowed configs: cached decode logits == training forward logits for
    the same prefix (the drift the review caught)."""
    import accelerate_tpu.nn as nn
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    nn.manual_seed(0)
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=64, sliding_window=16,
    )
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(1).integers(0, 256, (1, 48)).astype(np.int32)
    fwd_logits = np.asarray(model(nn.Tensor(jnp.asarray(ids)))["logits"].data)

    from accelerate_tpu.models.generation import generate

    # greedy decode's first token == argmax of the training-forward logits
    # at the last prefix position; with window 16 << 48 any full-causal
    # prefill would disagree (verified: removing the decode window breaks it)
    out = np.asarray(generate(model, jnp.asarray(ids), max_new_tokens=1))
    assert out.shape[1] == 49
    assert out[0, -1] == int(fwd_logits[0, -1].argmax())


def test_backward_matches_reference():
    q, k, v = _rand_qkv(s=512)
    w = 256

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, True, None, w)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = sdpa_reference(q, k, v, is_causal=True, window=w)
        return jnp.sum(o * jnp.cos(o))

    gq, gk, gv = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("window", [64, 200, 384])
def test_backward_narrowed_grid_parity(window):
    """Multi-tile narrowed dq/dkv kernel pair (block 128 at seq 512) vs the
    band reference — covers clamp-duplicate visits on both grid walks."""
    q, k, v = _rand_qkv(s=512)
    scale = 64 ** -0.5

    # route the backward through _flash_backward with small blocks
    out, lse = fa._flash_forward(
        q, k, v, scale, True, block_q=128, block_k=128,
        window=window, return_lse=True,
    )
    g = jnp.cos(out) - out * jnp.sin(out)  # d/do of sum(o*cos(o))
    gq, gk, gv = fa._flash_backward(
        q, k, v, out, lse[..., 0], g, scale, True,
        block_q=128, block_k=128, window=window,
    )

    def loss_ref(q, k, v):
        o = sdpa_reference(q, k, v, is_causal=True, window=window)
        return jnp.sum(o * jnp.cos(o))

    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("mode", ["ring", "all_to_all"])
@pytest.mark.parametrize("window", [8, 20])
def test_sequence_parallel_window_parity(mode, window):
    """Windowed SP attention (ring hop-skipping / Ulysses local band) on the
    8-device CPU mesh matches the single-device band reference, values and
    grads. Window 8 == chunk (out-of-band hops actually skip); 20 cuts
    through chunk boundaries."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.ops.ring_attention import sequence_parallel_attention
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    AcceleratorState._reset_state()
    mesh = AcceleratorState(
        parallelism_config=ParallelismConfig(sp_size=4, dp_size=2)
    ).mesh
    b, h, s, d = 2, 4, 32, 8  # chunk = 8 per sp device
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=True, window=window)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))

    out = jax.jit(
        lambda a, b_, c: sequence_parallel_attention(
            a, b_, c, mesh=mesh, is_causal=True, mode=mode, window=window
        )
    )(place(q), place(k), place(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)

    def sp_loss(q_, k_, v_):
        return sequence_parallel_attention(
            q_, k_, v_, mesh=mesh, is_causal=True, mode=mode, window=window
        ).sum()

    def ref_loss(q_, k_, v_):
        return sdpa_reference(q_, k_, v_, is_causal=True, window=window).sum()

    g_sp = jax.grad(sp_loss, argnums=(0, 1, 2))(place(q), place(k), place(v))
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge),
                                   rtol=5e-4, atol=1e-5)


@pytest.mark.parametrize("window", [64, 200])
def test_ring_flash_hop_windowed_parity(window):
    """The Pallas flash-hop windowed ring path (chunk 128): in-kernel band
    masking with traced offsets, the hop vjp's window threading, and the
    whole-hop band skip — forward and grads vs the band reference."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    import accelerate_tpu.ops.ring_attention as ra
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    AcceleratorState._reset_state()
    mesh = AcceleratorState(parallelism_config=ParallelismConfig(sp_size=2)).mesh
    b, h, s, d = 4, 2, 256, 64  # chunk = 128: MXU-tileable → flash hops
    # (b=4: the remaining mesh devices land on dp, so batch must divide dp)
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=True, window=window)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))

    import unittest.mock as mock

    with mock.patch.object(ra, "_FORCE_FLASH_HOPS", True):
        out = jax.jit(
            lambda a, b_, c: ra.ring_attention(
                a, b_, c, mesh=mesh, is_causal=True, window=window
            )
        )(place(q), place(k), place(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

        def ring_loss(q_, k_, v_):
            return ra.ring_attention(
                q_, k_, v_, mesh=mesh, is_causal=True, window=window
            ).sum()

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(
            place(q), place(k), place(v)
        )

    def ref_loss(q_, k_, v_):
        return sdpa_reference(q_, k_, v_, is_causal=True, window=window).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge),
                                   rtol=5e-4, atol=1e-5)


def test_window_requires_causal():
    q, k, v = _rand_qkv(s=128)
    with pytest.raises(ValueError, match="sliding window"):
        fa.flash_attention(q, k, v, False, None, 64)
    with pytest.raises(ValueError, match="sliding window"):
        sdpa_reference(q, k, v, is_causal=False, window=64)
    # SP entry points validate identically on sp>1 meshes (review finding:
    # the ring silently ignored the window there)
    from accelerate_tpu.ops.ring_attention import ring_attention
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    AcceleratorState._reset_state()
    mesh = AcceleratorState(parallelism_config=ParallelismConfig(sp_size=4)).mesh
    qs = jnp.zeros((1, 2, 32, 8))
    with pytest.raises(ValueError, match="sliding window"):
        ring_attention(qs, qs, qs, mesh=mesh, is_causal=False, window=8)


def test_mistral_bridge_parity():
    """transformers MistralForCausalLM converts through the bridge and
    matches the HF forward — including the sliding-window band (seq chosen
    longer than the window so the band actually bites)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    if not hasattr(transformers, "MistralForCausalLM"):
        pytest.skip("transformers build lacks Mistral")

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(
        transformers.MistralConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, sliding_window=8,
            tie_word_embeddings=False,
        )
    ).eval()
    ours = convert_torch_module(hf)
    assert ours.config.sliding_window == 8
    ids = np.random.default_rng(0).integers(0, 512, (2, 32), dtype=np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids, jnp.int32))["logits"].data)
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=3e-5)


def test_mistral_from_pretrained_dispatch(tmp_path):
    """from_pretrained infers the mistral architecture from config.json and
    loads through the Llama family with the window set (review finding: the
    dispatch registration was missing)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    if not hasattr(transformers, "MistralForCausalLM"):
        pytest.skip("transformers build lacks Mistral")

    from accelerate_tpu.utils.hf import from_pretrained

    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(
        transformers.MistralConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, sliding_window=8,
            tie_word_embeddings=False,
        )
    ).eval()
    hf.save_pretrained(str(tmp_path))
    ours = from_pretrained(str(tmp_path))
    assert ours.config.sliding_window == 8
    ids = np.random.default_rng(2).integers(0, 512, (1, 32), dtype=np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids, jnp.int32))["logits"].data)
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=3e-5)


def test_llama_sliding_window_config():
    """sliding_window changes the model output vs full causal, and matches a
    reference-path run of the same model."""
    import os

    import accelerate_tpu.nn as nn
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg_kw = dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=256,
    )
    ids = nn.Tensor(jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 256)), jnp.int32
    ))

    def logits_for(**extra):
        nn.manual_seed(0)
        model = LlamaForCausalLM(LlamaConfig(**cfg_kw, **extra))
        return np.asarray(model(ids)["logits"].data)

    full = logits_for()
    windowed = logits_for(sliding_window=128)
    assert not np.allclose(full, windowed)  # the band actually applies
    # early positions (inside the window) agree; late positions differ
    np.testing.assert_allclose(full[:, :64], windowed[:, :64], atol=1e-4)
    assert not np.allclose(full[:, -1], windowed[:, -1])


def test_window_tiles_formula():
    """The ONE band-geometry formula all three narrowed walks share: covers
    exactly the tiles a band can touch (never under, at most one spare)."""
    for block in (128, 256, 512):
        for window in (1, 127, 128, 129, 200, 511, 512, 513, 1024):
            num_tiles = 4096 // block
            wt = fa._window_tiles(window, block, num_tiles)
            # exact requirement: a q row at tile edge reaches back window-1
            # positions → floor((window + block - 2) / block) + 1 tiles
            needed = min(num_tiles, (window + block - 2) // block + 1)
            assert needed <= wt <= needed + 1, (block, window, wt, needed)
            assert wt <= num_tiles


def test_dispatcher_forced_paths_honor_window(monkeypatch):
    """ACCELERATE_TPU_FLASH=0 (XLA path) and =1 (Pallas path) both apply the
    band — insurance on the sdpa_tpu plumbing either side of the fork."""
    from accelerate_tpu.ops.attention import sdpa_tpu

    q, k, v = _rand_qkv(s=256)
    ref = sdpa_reference(q, k, v, is_causal=True, window=96)
    monkeypatch.setenv("ACCELERATE_TPU_FLASH", "0")
    out_xla = sdpa_tpu(q, k, v, is_causal=True, window=96)
    np.testing.assert_allclose(np.asarray(out_xla), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    monkeypatch.setenv("ACCELERATE_TPU_FLASH", "1")
    out_pallas = sdpa_tpu(q, k, v, is_causal=True, window=96)
    np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
