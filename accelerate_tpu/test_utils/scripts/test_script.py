"""The flagship end-to-end correctness suite, run through the launcher.

Counterpart of ``/root/reference/src/accelerate/test_utils/scripts/test_script.py``
(process control :93, RNG sync :174, DL preparation :192-363, mock_training
:436-454, split_between_processes :519, trigger sync :665-819).  ``accelerate-tpu
test`` runs exactly this script for end users; the pytest suite launches it on
an 8-virtual-device CPU mesh (SURVEY.md §4 Pattern 2/3).

Every check works at any device/process count, including one.
"""

from __future__ import annotations

import os

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, PartialState, prepare_data_loader, set_seed
from accelerate_tpu.data_loader import skip_first_batches
from accelerate_tpu.nn import Tensor
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
from accelerate_tpu.utils.random import synchronize_rng_states


def test_state():
    state = PartialState()
    assert state.num_devices >= 1
    assert 0 <= state.process_index < state.num_processes
    state.wait_for_everyone()

    # split_between_processes covers everything exactly once across processes
    items = list(range(17))
    with state.split_between_processes(items) as mine:
        local = list(mine)
    assert len(local) >= 1
    gathered = []
    # gather via object gather only matters multi-process; single process is identity
    if state.num_processes == 1:
        assert local == items
    print("state ok")


def test_rng_sync():
    synchronize_rng_states(["jax"])
    import jax

    draw = jax.random.uniform(nn.random.default_rng.next_key(), (4,))
    arr = np.asarray(draw)
    # All processes/devices must draw identical numbers after a sync
    acc = Accelerator()
    gathered = np.asarray(acc.gather(arr.reshape(1, -1)))
    assert np.allclose(gathered, gathered[0]), "RNG out of sync across shards"
    print("rng sync ok")


def test_rng_types_deep():
    """Per-source RNG sync (reference rng_sync_check test_script.py:174):
    after synchronize_rng_states each source draws identically everywhere;
    a process-specific seed then diverges the local streams again."""
    import random as pyrandom

    import jax

    acc = Accelerator()
    synchronize_rng_states(["numpy", "python", "jax"])
    from accelerate_tpu.utils import operations as ops

    draws = np.asarray(
        [np.random.rand(), pyrandom.random(), float(jax.random.uniform(nn.random.default_rng.next_key(), ()))],
        dtype=np.float64,
    )
    gathered = np.asarray(ops.gather_object([draws.tolist()]))
    assert np.allclose(gathered, gathered[0]), "per-source RNG out of sync"
    # device_specific seeding must DIVERGE processes (reference set_seed
    # device_specific=True) — only observable multi-process
    set_seed(1234, device_specific=True)
    local = np.random.rand()
    locals_all = ops.gather_object([local])
    if acc.num_processes > 1:
        assert len(set(np.round(locals_all, 12))) > 1, "device_specific seed identical"
    print("rng types deep ok")


def test_object_collectives():
    """gather_object / broadcast_object_list on arbitrary picklables
    (reference test_script.py:min gather_object + broadcast sections)."""
    from accelerate_tpu.utils import operations as ops

    acc = Accelerator()
    mine = {"rank": acc.process_index, "tag": f"p{acc.process_index}"}
    everyone = ops.gather_object([mine])
    assert len(everyone) == acc.num_processes
    assert sorted(d["rank"] for d in everyone) == list(range(acc.num_processes))

    payload = ["from-main", {"nested": 7}] if acc.is_main_process else [None, None]
    out = ops.broadcast_object_list(payload)
    assert out[0] == "from-main" and out[1] == {"nested": 7}, out
    print("object collectives ok")


def test_join_uneven_inputs():
    """join_uneven_inputs contract (reference test_script.py join section):
    under SPMD the global loader already evens batches, so the context is a
    documented pass-through — training inside it must work unchanged, and
    overriding even_batches warns rather than silently changing math."""
    Accelerator._reset_state()  # clear any config a prior check installed
    acc = Accelerator()
    model = RegressionModel()
    opt = optim.SGD(model.parameters(), lr=0.05)
    model, opt = acc.prepare(model, opt)
    with acc.join_uneven_inputs([model]):
        for i in range(3):  # same count everywhere: SPMD programs are uniform
            opt.zero_grad()
            x = Tensor(np.full((2, 1), float(i), np.float32))
            loss = nn.F.mse_loss(model(x), Tensor(np.zeros((2, 1), np.float32)))
            acc.backward(loss)
            opt.step()
    acc.wait_for_everyone()
    from accelerate_tpu.utils import operations as ops

    a = float(np.asarray(model.a.data))
    vals = ops.gather_object([a])
    assert all(abs(v - vals[0]) < 1e-6 for v in vals), vals
    print("join_uneven_inputs ok")


def _dataset(n):
    return [{"x": np.float32(i), "y": np.float32(2 * i + 1)} for i in range(n)]


def _collect_seen(acc, dl) -> list[int]:
    """Iterate a loader, gather across shards, return the flat index list."""
    seen: list[int] = []
    for batch in dl:
        x = np.asarray(acc.gather(batch["x"]))
        seen.extend(int(v) for v in x.ravel())
    return seen


def test_dataloader_coverage():
    acc = Accelerator()
    n, bs = 22, 4  # uneven tail: 22 % (4*shards) != 0 for any shard count >1
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    seen = _collect_seen(acc, dl)
    # even_batches loops back to fill final batch: every index appears >= 1×
    assert set(seen) == set(range(n)), f"coverage broken: {sorted(set(seen))[:10]}..."
    assert len(seen) >= n
    print("dataloader coverage ok")


def test_dataloader_even_batches_off():
    acc = Accelerator()
    shards = max(1, acc.num_devices)
    n, bs = 22, 4
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs, even_batches=False)
    seen = _collect_seen(acc, dl)
    # nothing is duplicated when even_batches is off
    assert len(seen) == len(set(seen)), "even_batches=False must not duplicate"
    assert set(seen) <= set(range(n))
    print("dataloader even_batches=False ok")


def test_dispatch_loader():
    """Dispatch mode: rank 0 reads, peers receive the global batch via
    broadcast (reference DataLoaderDispatcher, data_loader.py:696) — must
    cover the dataset exactly once at any device/process count (n is sized
    to divide the global batch so no even_batches loop-back occurs)."""
    acc = Accelerator()
    bs = 4
    n = 2 * bs * max(1, acc.num_devices)
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs, dispatch_batches=True)
    seen = _collect_seen(acc, dl)
    assert sorted(seen) == list(range(n)), f"dispatch coverage broken: {sorted(seen)}"
    print("dispatch loader ok")


def test_skip_first_batches():
    acc = Accelerator()
    n, bs = 128, 4  # ≥4 global batches at any shard count ≤ 8
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    full = [np.asarray(acc.gather(b["x"])).ravel() for b in dl]
    skipped = skip_first_batches(dl, 2)
    rest = [np.asarray(acc.gather(b["x"])).ravel() for b in skipped]
    assert len(rest) == len(full) - 2
    for a, b in zip(full[2:], rest):
        assert np.array_equal(a, b), "skip_first_batches changed batch contents"
    print("skip_first_batches ok")


def mock_training():
    """Distributed training must match a numpy single-process baseline
    exactly (reference test_script.py:436: trained weights equality)."""
    set_seed(42)
    n, bs, lr, epochs = 64, 4, 0.1, 2
    data = RegressionDataset(length=n, seed=96)

    acc = Accelerator()
    model = RegressionModel()
    ds = [{"x": data.x[i], "y": data.y[i]} for i in range(n)]
    dl = prepare_data_loader(dataset=ds, batch_size=bs)
    opt = optim.SGD(model.parameters(), lr=lr)
    model, opt, dl = acc.prepare(model, opt, dl)

    for _ in range(epochs):
        for batch in dl:
            opt.zero_grad()
            pred = model(batch["x"])
            loss = nn.F.mse_loss(pred, Tensor(batch["y"]))
            acc.backward(loss)
            opt.step()

    # numpy baseline over the same global batch sequence
    a, b = 0.0, 0.0
    gbs = dl.total_batch_size
    order = np.arange(n)
    for _ in range(epochs):
        for start in range(0, n, gbs):
            idx = order[start : start + gbs]
            if len(idx) < gbs:  # even_batches loop-back
                idx = np.concatenate([idx, order[: gbs - len(idx)]])
            x, y = data.x[idx], data.y[idx]
            pred = a * x + b
            grad_a = float(np.mean(2 * (pred - y) * x))
            grad_b = float(np.mean(2 * (pred - y)))
            a -= lr * grad_a
            b -= lr * grad_b

    got_a = float(np.asarray(model.a.data))
    got_b = float(np.asarray(model.b.data))
    assert abs(got_a - a) < 1e-3, f"a: {got_a} vs baseline {a}"
    assert abs(got_b - b) < 1e-3, f"b: {got_b} vs baseline {b}"
    print(f"mock training ok (a={got_a:.4f}, b={got_b:.4f})")


def _regression_setup(lr=0.1, **acc_kwargs):
    # these checks vary Accelerator config (precision, accumulation), and
    # AcceleratorState is a Borg that refuses conflicting re-init — reset
    # first (the jax.distributed rendezvous is module-global and survives)
    Accelerator._reset_state()
    set_seed(42)
    acc = Accelerator(**acc_kwargs)
    model = RegressionModel()
    opt = optim.SGD(model.parameters(), lr=lr)
    model, opt = acc.prepare(model, opt)
    return acc, model, opt


def mock_training_accumulate():
    """Gradient accumulation parity (reference test_script.py training
    section): two half-batch micro-steps under accumulate() must produce
    the same update as one full-batch step."""
    data = RegressionDataset(length=16, seed=11)
    x, y = data.x.astype(np.float32), data.y.astype(np.float32)

    acc, model, opt = _regression_setup(gradient_accumulation_steps=2)
    for lo in (0, 8):
        with acc.accumulate(model):
            pred = model(Tensor(x[lo : lo + 8].reshape(-1, 1)))
            loss = nn.F.mse_loss(pred, Tensor(y[lo : lo + 8].reshape(-1, 1)))
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
    a_acc = float(np.asarray(model.a.data))

    acc2, model2, opt2 = _regression_setup()
    opt2.zero_grad()
    pred = model2(Tensor(x.reshape(-1, 1)))
    loss = nn.F.mse_loss(pred, Tensor(y.reshape(-1, 1)))
    acc2.backward(loss)
    opt2.step()
    a_full = float(np.asarray(model2.a.data))
    assert abs(a_acc - a_full) < 1e-5, f"accumulate parity: {a_acc} vs {a_full}"
    print("mock training accumulate ok")


def mock_training_capture_parity():
    """compile_step replays must match eager stepping bit-for-bit on the
    same data (the capture engine is the default execution path on TPU)."""
    data = RegressionDataset(length=8, seed=5)
    x = Tensor(data.x.astype(np.float32).reshape(-1, 1))
    y = Tensor(data.y.astype(np.float32).reshape(-1, 1))

    def body(acc, model, opt):
        def fn(xb, yb):
            opt.zero_grad()
            loss = nn.F.mse_loss(model(xb), yb)
            acc.backward(loss)
            opt.step()
            return loss

        return fn

    acc_e, model_e, opt_e = _regression_setup()
    fn_e = body(acc_e, model_e, opt_e)
    eager = [float(fn_e(x, y)) for _ in range(3)]

    acc_c, model_c, opt_c = _regression_setup()
    step = acc_c.compile_step(body(acc_c, model_c, opt_c))
    captured = [float(step(x, y)) for _ in range(3)]
    assert np.allclose(eager, captured, rtol=1e-6), (eager, captured)
    assert abs(float(np.asarray(model_e.a.data)) - float(np.asarray(model_c.a.data))) < 1e-6
    print("mock training capture parity ok")


def mock_training_bf16():
    """bf16 mixed precision trains and keeps fp32 master accuracy
    (reference test_script.py fp16/bf16 training variants)."""
    data = RegressionDataset(length=32, seed=7)
    acc, model, opt = _regression_setup(mixed_precision="bf16", lr=0.05)
    x = Tensor(data.x.astype(np.float32).reshape(-1, 1))
    y = Tensor(data.y.astype(np.float32).reshape(-1, 1))
    losses = []
    for _ in range(6):
        opt.zero_grad()
        with acc.autocast():
            loss = nn.F.mse_loss(model(x), y)
        acc.backward(loss)
        opt.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    print("mock training bf16 ok")


def test_dispatch_grid():
    """Dispatch-mode loader over the same grid/rules as the sharded loader
    (reference central_dl_preparation_check, test_script.py:255-316) — one
    shared grid walker so the two modes cannot drift."""
    _dl_grid_check(dispatch_batches=True, ns=(22,), label="dispatch grid")


def test_gather_for_metrics():
    """Duplicate-tail truncation: gathered sample count == dataset length
    (reference gather_for_metrics remainder logic, accelerator.py:2601)."""
    acc = Accelerator()
    n, bs = 22, 4
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    dl = acc.prepare(dl)
    seen = []
    for batch in dl:
        xs = acc.gather_for_metrics(batch["x"])
        seen.extend(int(v) for v in np.asarray(xs).ravel())
    assert sorted(seen) == list(range(n)), (
        f"gather_for_metrics must dedup the looped tail: got {len(seen)} items"
    )
    print("gather_for_metrics ok")


def test_save_load_roundtrip():
    """Multi-process checkpoint: save (rank-gated writes + per-process RNG),
    perturb, load, assert exact restoration on every process."""
    import shutil

    acc = Accelerator()
    model = RegressionModel()
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    # one training step so optimizer state is non-trivial
    ds = [{"x": np.float32(i), "y": np.float32(2 * i + 1)} for i in range(8)]
    dl = acc.prepare(prepare_data_loader(dataset=ds, batch_size=4))
    batch = next(iter(dl))
    opt.zero_grad()
    loss = nn.F.mse_loss(model(batch["x"]), Tensor(batch["y"]))
    acc.backward(loss)
    opt.step()
    saved_a = float(np.asarray(model.a.data))

    from accelerate_tpu.test_utils.testing import launch_scoped_tmpdir

    ckpt = launch_scoped_tmpdir("acc_tpu_ckpt")
    try:
        acc.save_state(ckpt)
        model.a.data = model.a.data * 0.0 + 123.0  # clobber
        acc.load_state(ckpt)
        got = float(np.asarray(model.a.data))
        assert abs(got - saved_a) < 1e-7, f"restore mismatch: {got} vs {saved_a}"
        acc.wait_for_everyone()
    finally:
        if acc.is_main_process:
            shutil.rmtree(ckpt, ignore_errors=True)
    print("save/load roundtrip ok")


def test_trigger():
    """Trigger sync ACROSS ranks: only main raises the flag, every process
    must see it at the check (reference test_script.py:786)."""
    acc = Accelerator()
    acc.flag_tensor = None
    assert acc.check_trigger() is False
    if acc.is_main_process:
        acc.set_trigger()
    assert acc.check_trigger() is True, "trigger set on main was not seen here"
    assert acc.check_trigger() is False  # reset after firing
    print("trigger ok")


def process_execution_check():
    """main_process_first ordering + on_*_process decorators (reference
    test_script.py:93-165)."""
    import contextlib
    import io
    import time

    import socket

    from accelerate_tpu.utils import operations as ops

    acc = Accelerator()
    # the file-ordering half assumes a shared filesystem; on a real pod each
    # host has its own disk, so gate it on every rank seeing one hostname
    hosts = ops.gather_object([socket.gethostname()])
    if len(set(hosts)) == 1:
        path = os.path.join(
            os.environ.get("ACCELERATE_TPU_LAUNCH_TMP", "."),
            "check_main_process_first.txt",
        )
        with acc.main_process_first():
            if acc.is_main_process:
                time.sleep(0.1)  # ensure main would lose a pure race
                with open(path, "a+") as f:
                    f.write("Currently in the main process\n")
            else:
                with open(path, "a+") as f:
                    f.write("Now on another process\n")
        acc.wait_for_everyone()
        if acc.is_main_process:
            try:
                with open(path) as f:
                    text = f.read()
                assert text.startswith("Currently in the main process\n"), text
                assert text.count("Now on another process\n") == acc.num_processes - 1, text
            finally:
                os.unlink(path)
        acc.wait_for_everyone()

    f = io.StringIO()
    with contextlib.redirect_stdout(f):
        acc.on_main_process(lambda: print("from main"))()
    assert (f.getvalue().strip() == "from main") == acc.is_main_process

    f = io.StringIO()
    with contextlib.redirect_stdout(f):
        acc.on_last_process(lambda: print("from last"))()
    assert (f.getvalue().strip() == "from last") == acc.is_last_process

    for idx in range(acc.num_processes):
        f = io.StringIO()
        with contextlib.redirect_stdout(f):
            acc.on_process(lambda: print(f"from {idx}"), process_index=idx)()
        assert (f.getvalue().strip() == f"from {idx}") == (acc.process_index == idx)
    print("process execution ok")


def test_split_between_processes_list():
    """Reference test_script.py:698: even split, and padding gives the last
    process the extra items."""
    import math

    state = PartialState()
    data = list(range(2 * state.num_processes))
    with state.split_between_processes(data) as results:
        assert len(results) == 2, f"rank {state.process_index}: {len(results)}"

    data = list(range(3 * state.num_processes - 1))
    with state.split_between_processes(data, apply_padding=True) as results:
        if state.is_last_process:
            per = math.ceil(len(data) / state.num_processes)
            assert len(results) == per, f"padding broke: {len(results)} != {per}"
    state.wait_for_everyone()
    print("split_between_processes list ok")


def test_split_between_processes_nested_dict():
    """Reference test_script.py:717: dict of list/str/array splits leafwise
    and consistently."""
    state = PartialState()
    n = 2 * state.num_processes
    a = list(range(n))
    b = [chr(ord("a") + i) for i in range(n)]
    c = np.arange(n, dtype=np.float32)
    with state.split_between_processes({"a": a, "b": b, "c": c}) as results:
        lo = 2 * state.process_index
        assert results["a"] == a[lo : lo + 2], results["a"]
        assert results["b"] == b[lo : lo + 2], results["b"]
        assert np.allclose(np.asarray(results["c"]), c[lo : lo + 2]), results["c"]
    state.wait_for_everyone()
    print("split_between_processes nested dict ok")


def test_split_between_processes_tensor():
    """Reference test_script.py:755: array inputs split on the batch dim."""
    state = PartialState()
    data = np.arange(4 * state.num_processes).reshape(state.num_processes, 4)
    with state.split_between_processes(data) as results:
        expect = data[state.process_index : state.process_index + 1]
        assert np.allclose(np.asarray(results), expect), np.asarray(results)
    state.wait_for_everyone()
    print("split_between_processes tensor ok")


def test_split_between_processes_evenly():
    """Reference test_script.py:768: 17 items — the first `extras` ranks get
    one more item each, nothing is lost."""
    state = PartialState()
    data = list(range(17))
    per, extras = divmod(len(data), state.num_processes)
    with state.split_between_processes(data) as results:
        want = per + 1 if state.process_index < extras else per
        assert len(results) == want, f"rank {state.process_index}: {len(results)} != {want}"
    state.wait_for_everyone()
    print("split_between_processes evenly ok")


def test_print_in_order():
    """in_order logging: every rank prints, outputs don't interleave
    (reference print_in_order via state.print / logging in_order)."""
    acc = Accelerator()
    for idx in range(acc.num_processes):
        if acc.process_index == idx:
            print(f"rank {idx} reporting in order")
        acc.wait_for_everyone()


def _dl_grid_check(dispatch_batches: bool, ns: tuple, label: str) -> None:
    """ONE grid walker for both loader modes: (n × batch_size ×
    even_batches × split_batches), asserting coverage + the exact
    loop-back count under even_batches and no-duplication otherwise."""
    acc = Accelerator()
    shards = max(1, acc.state.num_batch_shards)
    for n in ns:
        for bs in sorted({2, 4, shards}):
            for even_batches in (True, False):
                for split_batches in (True, False):
                    if split_batches and bs % shards != 0:
                        continue  # split mode needs a divisible global batch
                    dl = prepare_data_loader(
                        dataset=_dataset(n),
                        batch_size=bs,
                        dispatch_batches=dispatch_batches,
                        even_batches=even_batches,
                        split_batches=split_batches,
                    )
                    seen = _collect_seen(acc, dl)
                    cell = (
                        f"dispatch={dispatch_batches} n={n} bs={bs} "
                        f"even={even_batches} split={split_batches}"
                    )
                    if even_batches:
                        assert set(seen) == set(range(n)), f"{cell}: coverage broken"
                        gbs = dl.total_batch_size
                        want = ((n + gbs - 1) // gbs) * gbs
                        assert len(seen) == want, f"{cell}: {len(seen)} != {want}"
                    else:
                        assert len(seen) == len(set(seen)), f"{cell}: duplicated"
                        assert set(seen) <= set(range(n)), f"{cell}: out of range"
    print(f"{label} ok")


def test_uneven_tail_grid():
    """(batch_size × even_batches × split_batches) grid under the REAL
    launcher (reference dl_preparation_check/central grids,
    test_script.py:192-316): coverage and duplication rules hold in every
    cell."""
    _dl_grid_check(dispatch_batches=False, ns=(18, 22), label="uneven-tail grid")


def main():
    acc = Accelerator()
    state = acc.state
    if state.is_main_process:
        print(f"** Testing on {state.num_devices} device(s), "
              f"{state.num_processes} process(es) **")
    test_state()
    process_execution_check()
    test_print_in_order()
    test_split_between_processes_list()
    test_split_between_processes_nested_dict()
    test_split_between_processes_tensor()
    test_split_between_processes_evenly()
    test_rng_sync()
    test_rng_types_deep()
    test_object_collectives()
    test_dataloader_coverage()
    test_dataloader_even_batches_off()
    test_uneven_tail_grid()
    test_dispatch_loader()
    test_dispatch_grid()
    test_skip_first_batches()
    test_gather_for_metrics()
    mock_training()
    mock_training_accumulate()
    mock_training_capture_parity()
    mock_training_bf16()
    test_join_uneven_inputs()
    test_save_load_roundtrip()
    test_trigger()
    state.wait_for_everyone()
    if state.is_main_process:
        print("All checks passed")


if __name__ == "__main__":
    main()
