import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderShard,
    GlobalBatchSampler,
    IterableDatasetShard,
    SeedableRandomSampler,
    SequentialSampler,
    default_collate,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import AcceleratorState, GradientState


def make_global(n, batch_size, num_shards, **kw):
    bs = BatchSampler(SequentialSampler(n), batch_size, drop_last=kw.pop("drop_last", False))
    return GlobalBatchSampler(bs, num_shards, **kw)


def test_seedable_sampler_deterministic():
    s1 = SeedableRandomSampler(10, seed=3, epoch=0)
    s2 = SeedableRandomSampler(10, seed=3, epoch=0)
    assert list(s1) == list(s2)
    s2.set_epoch(1)
    assert list(s1) != list(s2)


def test_global_batch_sampler_exact_fit():
    # 8 samples, bs 2, 2 shards → 2 steps, no remainder
    gs = make_global(8, 2, 2)
    groups = list(gs)
    assert groups == [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    assert gs.remainder == 0


def test_global_batch_sampler_uneven_tail_loops_back():
    # 10 samples, bs 2, 2 shards → 3 steps; last group has batches [8,9] only
    # → loop back to the epoch's first samples
    gs = make_global(10, 2, 2)
    groups = list(gs)
    assert groups[0] == [[0, 1], [2, 3]]
    assert groups[1] == [[4, 5], [6, 7]]
    assert groups[2] == [[8, 9], [0, 1]]
    assert gs.remainder == 2


def test_global_batch_sampler_short_final_batch():
    # 7 samples, bs 2, 2 shards → [0,1],[2,3] | [4,5],[6,+pad]
    gs = make_global(7, 2, 2)
    groups = list(gs)
    assert groups[1][0] == [4, 5]
    assert groups[1][1][0] == 6
    assert gs.remainder == 1
    # padded index comes from the start of the epoch stream
    assert groups[1][1][1] == 0


def test_global_batch_sampler_drop_last():
    gs = make_global(7, 2, 2, drop_last=True)
    groups = list(gs)
    # batches: [0,1],[2,3],[4,5] → one full group + loop-back group
    assert groups[0] == [[0, 1], [2, 3]]
    assert groups[1] == [[4, 5], [0, 1]]
    assert gs.remainder == 2


def test_global_batch_sampler_even_false_ragged():
    # SPMD: a ragged tail group (one shard would get [8, 9], the other
    # nothing) has no uniform global batch, so it is dropped entirely
    gs = make_global(10, 2, 2, even_batches=False)
    groups = list(gs)
    assert groups == [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    assert gs.remainder == 0
    assert len(gs) == len(groups)


def test_global_batch_sampler_split_batches():
    # split: each sampler batch (size 4) IS the global batch, split 2 ways
    bs = BatchSampler(SequentialSampler(8), 4)
    gs = GlobalBatchSampler(bs, 2, split_batches=True)
    groups = list(gs)
    assert groups == [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    assert gs.total_batch_size == 4


def test_split_batches_requires_divisible():
    bs = BatchSampler(SequentialSampler(8), 3)
    with pytest.raises(ValueError):
        GlobalBatchSampler(bs, 2, split_batches=True)


def test_batch_sampler_shard_view():
    bs = BatchSampler(SequentialSampler(10), 2)
    shard0 = BatchSamplerShard(bs, 2, 0)
    shard1 = BatchSamplerShard(bs, 2, 1)
    assert list(shard0) == [[0, 1], [4, 5], [8, 9]]
    assert list(shard1) == [[2, 3], [6, 7], [0, 1]]
    assert len(shard0) == 3
    assert shard0.total_batch_size == 4


@pytest.mark.parametrize("n,batch_size,num_shards", [(17, 3, 4), (32, 4, 8), (5, 2, 4)])
def test_global_sampler_invariants(n, batch_size, num_shards):
    """Every group has num_shards batches of exactly batch_size indices."""
    gs = make_global(n, batch_size, num_shards)
    for group in gs:
        assert len(group) == num_shards
        for shard in group:
            assert len(shard) == batch_size


def test_iterable_dataset_shard():
    data = list(range(10))
    shard0 = IterableDatasetShard(data, batch_size=2, num_processes=2, process_index=0)
    shard1 = IterableDatasetShard(data, batch_size=2, num_processes=2, process_index=1)
    out0, out1 = list(shard0), list(shard1)
    assert out0 == [0, 1, 4, 5, 8, 9]
    assert out1 == [2, 3, 6, 7, 0, 1]  # tail looped back


def test_default_collate():
    samples = [{"x": np.ones(2), "y": 1}, {"x": np.zeros(2), "y": 2}]
    batch = default_collate(samples)
    assert batch["x"].shape == (2, 2)
    np.testing.assert_array_equal(batch["y"], [1, 2])


def test_dataloader_shard_end_to_end():
    AcceleratorState()  # default 8-dev dp mesh
    dataset = [{"x": np.full((4,), float(i)), "label": i} for i in range(20)]
    dl = prepare_data_loader(dataset=dataset, batch_size=2, shuffle=False)
    gs = GradientState()
    batches = []
    for batch in dl:
        assert isinstance(batch["x"], jax.Array)
        assert batch["x"].shape == (16, 4)  # 2 per shard × 8 shards
        batches.append(batch)
    assert len(batches) == 2
    assert gs.end_of_dataloader is False  # loader deregistered after loop
    # remainder: 20 samples → step2 needs 32-20=12 dupes... second group short
    # total capacity 2 steps × 16 = 32 → remainder 12
    assert dl.remainder == 12


def test_dataloader_gradient_state_signaling():
    AcceleratorState()
    dataset = [{"x": np.ones(2)} for _ in range(32)]
    dl = prepare_data_loader(dataset=dataset, batch_size=2)
    gs = GradientState()
    flags = []
    for _ in dl:
        flags.append((gs.end_of_dataloader, gs.remainder))
    assert flags[0] == (False, -1)
    assert flags[-1] == (True, 0)


def test_dataloader_shuffle_reproducible_and_epoch_varies():
    AcceleratorState()
    dataset = [{"x": np.array([i])} for i in range(32)]
    dl = prepare_data_loader(dataset=dataset, batch_size=2, shuffle=True, data_seed=7)
    first_epoch = [b["x"].tolist() for b in dl]
    dl2 = prepare_data_loader(dataset=dataset, batch_size=2, shuffle=True, data_seed=7)
    assert [b["x"].tolist() for b in dl2] == first_epoch
    second_epoch = [b["x"].tolist() for b in dl]  # dl.epoch advanced
    assert second_epoch != first_epoch


def test_skip_first_batches():
    AcceleratorState()
    dataset = [{"x": np.array([i])} for i in range(32)]
    dl = prepare_data_loader(dataset=dataset, batch_size=2)
    all_batches = [b["x"].tolist() for b in dl]
    dl.epoch = 0  # reset epoch advance from iteration
    skipped = skip_first_batches(dl, 1)
    rest = [b["x"].tolist() for b in skipped]
    assert rest == all_batches[1:]


def test_streaming_iterable_dataset():
    AcceleratorState()

    def gen():
        for i in range(20):
            yield {"x": np.array([i], dtype=np.float32)}

    class Stream:
        def __iter__(self):
            return gen()

    dl = prepare_data_loader(dataset=Stream(), batch_size=2)
    batches = [b for b in dl]
    assert batches[0]["x"].shape == (16, 1)
    assert len(batches) == 2
    assert dl.remainder == 12


def test_prepare_torch_dataloader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset

    AcceleratorState()
    ds = TensorDataset(torch.arange(40, dtype=torch.float32).reshape(20, 2))
    torch_dl = DataLoader(ds, batch_size=2, shuffle=False)
    dl = prepare_data_loader(torch_dl)
    batch = next(iter(dl))
    (x,) = batch
    assert isinstance(x, jax.Array)
    assert x.shape == (16, 2)


def test_dataloader_len():
    AcceleratorState()
    dataset = [{"x": np.array([i])} for i in range(32)]
    dl = prepare_data_loader(dataset=dataset, batch_size=2)
    assert len(dl) == 2
    assert dl.total_batch_size == 16


def test_global_batch_sampler_even_false_len_matches_iter():
    """__len__ must count only yielded groups (code-review regression):
    a trailing short batch poisons its whole group."""
    for n, bs, shards in [(10, 3, 2), (12, 3, 2), (9, 3, 2), (22, 4, 8), (10, 2, 2)]:
        gs = make_global(n, bs, shards, even_batches=False)
        assert len(list(gs)) == len(gs), (n, bs, shards)


def test_sampler_accessors_and_total_length():
    """get_sampler/set_sampler/total_dataset_length (reference
    data_loader.py:624-641): swapping the index sampler between epochs
    changes the visit order."""
    from accelerate_tpu import Accelerator

    Accelerator._reset_state()
    Accelerator()
    ds = [{"x": np.float32(i)} for i in range(16)]
    dl = prepare_data_loader(ds, batch_size=2)
    assert dl.total_dataset_length == 16
    sampler = dl.get_sampler()
    assert sampler is not None

    class Reversed:
        def __iter__(self):
            return iter(range(15, -1, -1))

        def __len__(self):
            return 16

    dl.set_sampler(Reversed())
    first = next(iter(dl))
    assert float(np.asarray(first["x"]).ravel()[0]) == 15.0
