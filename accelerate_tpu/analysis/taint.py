"""Rank-divergence taint model — the dataflow core behind the
``collective-divergence`` rule family.

The worst bug class in a multi-process mesh program is a collective
(gather, vote, ``load_state``, ``fleet.resize``) guarded by **rank-divergent
state**: only some ranks enter the collective and the mesh deadlocks.  This
module gives the analyzer a semantics for "rank-divergent":

* **sources** mint divergent values — rank identity reads
  (``process_index`` / ``is_main_process``), rank-local retained telemetry
  records (``serving_signal`` / ``serving_events``, docs/telemetry.md), env
  vars documented as per-host (``LOCAL_RANK``-shaped keys), filesystem
  probes (each host sees its own disk), wall-clock reads, and host identity;
* **propagation** carries taint through assignments, returns, call
  arguments, method calls on a tainted receiver, and attribute/subscript
  stores on local (non-``self``) receivers;
* **kills** erase taint at the documented symmetry points: a value derived
  from an all-ranks merge (``gather_object`` / ``all_gather`` / ``psum`` /
  ``broadcast``) or from an ``agree_*`` pure merge is the SAME on every
  rank, however rank-local its inputs were (docs/elastic.md);
* **exemption** — a branch conjoined with a single-process world-size test
  (``not _multi_process()``, ``num_processes == 1``) never executes on a
  multi-process run, so divergence inside it is moot.  This is exactly the
  sanctioned PR-13 fix shape for the serving-signal gate
  (fleet/autopilot.py), so the linter recognizes the fix it once forced.

:class:`FunctionTaint` runs a per-function fixpoint at Name granularity.
It serves two callers: ``program.extract_summary`` uses it with no
cross-module knowledge to digest each function's *return-divergence*
(direct, or pending on named callees — the whole-program fixpoint in
``program.ProgramGraph`` resolves those), and the rule re-runs it with the
resolved ``divergent_aliases`` map so call sites of divergent-returning
functions taint immediately.

Documented approximations (kept deliberately, each in the safe direction
for its purpose): parameters start clean (cross-function argument taint is
not tracked — a false-negative risk only); ``self.x = tainted`` does not
taint other methods' ``self.x`` reads (false-negative); comprehension
binders leak into the function scope (false-positive, caught by fixtures);
seeded ``random`` streams are NOT sources (seeding is the documented way to
keep them symmetric).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import dotted_name, iter_own_nodes

# ---------------------------------------------------------------------------
# source tables
# ---------------------------------------------------------------------------

# attribute reads (and accessor calls) that ARE rank identity / rank-local
# state wherever they appear.  ``serving_events`` is the rank-local retained
# record list (docs/telemetry.md: serving records live on the rank that owns
# the hub); ``fleet_events`` is deliberately absent — the kind="fleet" skew
# record is REQUIRED to be rank-symmetric (built from an all-ranks gather,
# the PR-13 contract documented in docs/telemetry.md).
DIVERGENT_ATTRS = frozenset(
    {
        "process_index",
        "local_process_index",
        "is_main_process",
        "is_local_main_process",
        "is_last_process",
        "serving_events",
    }
)

# call leaves that mint a rank-divergent value regardless of receiver
_DIVERGENT_CALL_LEAVES = frozenset(
    {
        "serving_signal",  # newest rank-local serving record
        "gethostname",
        "getfqdn",
    }
)

_HOST_IDENT_CALLS = frozenset(
    {
        "os.getpid",
        "socket.gethostname",
        "socket.getfqdn",
        "platform.node",
        "uuid.getnode",
        "uuid.uuid1",
        "uuid.uuid4",
    }
)

_WALL_CLOCK_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "time.clock_gettime",
    }
)

# fs-probe call forms: full dotted stdlib paths, plus method leaves that are
# probes on ANY receiver (pathlib.Path and os.path share these spellings)
_FS_PROBE_CALLS = frozenset(
    {
        "os.listdir",
        "os.scandir",
        "os.walk",
        "os.stat",
        "os.path.exists",
        "os.path.isfile",
        "os.path.isdir",
        "os.path.islink",
        "os.path.getmtime",
        "os.path.getsize",
        "glob.glob",
        "glob.iglob",
    }
)
_FS_PROBE_METHOD_LEAVES = frozenset(
    {
        "exists",
        "is_file",
        "is_dir",
        "is_symlink",
        "listdir",
        "scandir",
        "glob",
        "iglob",
        "rglob",
        "getmtime",
        "getsize",
    }
)

# env keys documented as per-host/per-rank; symmetric config flags
# (ACCELERATE_*, TPU_PAD_MULTIPLE) deliberately don't match
_PER_HOST_ENV_RE = re.compile(
    r"(?:^|_)(LOCAL|HOST(?:NAME)?|RANK|NODE|WORKER)(?:_|$)|PROCESS_INDEX|PROCESS_ID"
)

# ---------------------------------------------------------------------------
# kills — documented symmetry points (docs/elastic.md, docs/telemetry.md)
# ---------------------------------------------------------------------------

_SYMMETRY_KILL_LEAVES = frozenset(
    {
        "gather_object",
        "all_gather",
        "all_gather_object",
        "allgather",
        "broadcast",
        "broadcast_object_list",
        "psum",
        "psum_scatter",
        "pmean",
        "pmax",
        "pmin",
        "all_to_all",
        "all_reduce",
        "sync_global_devices",
    }
)
_AGREE_PREFIX = "agree_"  # fleet pure merges: same inputs -> same answer

# ---------------------------------------------------------------------------
# collective sinks — ops every rank must enter together
# ---------------------------------------------------------------------------

_JAX_COLLECTIVE_LEAVES = frozenset(
    {
        "psum",
        "psum_scatter",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "all_to_all",
        "ppermute",
        "pshuffle",
    }
)
_JAX_PREFIXES = frozenset({"jax", "lax", "jnp"})
_FRAMEWORK_COLLECTIVE_LEAVES = frozenset(
    {
        "gather_object",
        "broadcast",
        "broadcast_object_list",
        "wait_for_everyone",
        "sync_global_devices",
        "vote_restore_point",
        "coordinated_rollback",
        "load_state",
        "save_state",
    }
)
_FLEET_VERB_LEAVES = frozenset({"resize", "grow"})

# builtins whose pending-callee edges are pure noise for the closure
_BUILTIN_NOISE = frozenset(
    {
        "abs", "all", "any", "bool", "bytes", "dict", "enumerate", "filter",
        "float", "format", "frozenset", "getattr", "hasattr", "id", "int",
        "isinstance", "issubclass", "iter", "len", "list", "map", "max",
        "min", "next", "print", "range", "repr", "reversed", "round", "set",
        "setattr", "sorted", "str", "sum", "tuple", "type", "vars", "zip",
    }
)

_MULTI_PROCESS_RE = re.compile(r"multi_process|is_distributed", re.IGNORECASE)
_WORLD_SIZE_RE = re.compile(
    r"num_processes|world_size|process_count", re.IGNORECASE
)

# ---------------------------------------------------------------------------
# rank-local-by-design modules (docs/telemetry.md §flight recorder)
# ---------------------------------------------------------------------------

# Postmortem writers run while the mesh may already be deadlocked: they read
# rank identity, the wall clock and the filesystem ON PURPOSE (the dump must
# name its rank and stamp its time), so the divergence scan would drown them
# in by-design findings.  The exemption is a CONTRACT, not a blanket waiver:
# in exchange, these modules must never contain a collective sink — a
# watchdog that gathers about the hang deadlocks the postmortem too.  The
# collective-divergence rule enforces the inverted direction on exactly this
# set (tests/test_graftlint.py pins both).
RANK_LOCAL_MODULE_SUFFIXES = frozenset(
    {
        "telemetry/flightrec.py",
        "telemetry/watchdog.py",
        "telemetry/trace_export.py",
    }
)


def rank_local_by_design(rel_path: str) -> bool:
    """True when ``rel_path`` names a module declared rank-local by design
    (per-rank postmortem writers — exempt from the divergence scan, but
    forbidden from ever issuing a collective)."""
    path = rel_path.replace("\\", "/")
    return any(path.endswith(suffix) for suffix in RANK_LOCAL_MODULE_SUFFIXES)


def _call_leaf(fn: ast.AST) -> Optional[str]:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _resolved(fn: ast.AST, module) -> str:
    r = module.resolve(fn) if module is not None else None
    return r or (dotted_name(fn) or "")


# ---------------------------------------------------------------------------
# world-size guards (the sanctioned single-process gate)
# ---------------------------------------------------------------------------

def _leaf_dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        node = node.func
    return dotted_name(node) or ""


def _world_size_expr(node: ast.AST) -> bool:
    d = _leaf_dotted(node)
    return bool(d and _WORLD_SIZE_RE.search(d))


def _multi_process_expr(node: ast.AST) -> bool:
    d = _leaf_dotted(node)
    return bool(d and _MULTI_PROCESS_RE.search(d))


def _world_size_is_many(node: ast.AST) -> bool:
    """``num_processes > 1`` / ``>= 2`` / ``!= 1`` shapes."""
    if isinstance(node, ast.Compare) and len(node.ops) == 1:
        op, l, r = node.ops[0], node.left, node.comparators[0]
        if _world_size_expr(l) and isinstance(r, ast.Constant):
            return (
                (isinstance(op, ast.Gt) and r.value == 1)
                or (isinstance(op, ast.GtE) and r.value == 2)
                or (isinstance(op, ast.NotEq) and r.value == 1)
            )
    return False


def single_process_conjunct(test: ast.AST) -> bool:
    """True when ``test`` (or one of its AND-conjuncts) restricts the branch
    to single-process runs — on a multi-process run the whole conjunction is
    uniformly False on EVERY rank, so nothing inside can diverge a mesh.
    Recognized spellings: ``not _multi_process()``, ``not state.use_distributed``
    -style multi-process predicates under ``not``, and world-size compares
    (``num_processes == 1`` / ``<= 1`` / ``< 2``, either operand order)."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        return any(single_process_conjunct(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _multi_process_expr(test.operand) or _world_size_is_many(
            test.operand
        )
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        op, l, r = test.ops[0], test.left, test.comparators[0]
        if _world_size_expr(l) and isinstance(r, ast.Constant):
            return (
                (isinstance(op, ast.Eq) and r.value == 1)
                or (isinstance(op, ast.LtE) and r.value == 1)
                or (isinstance(op, ast.Lt) and r.value == 2)
            )
        if _world_size_expr(r) and isinstance(l, ast.Constant):
            return (
                (isinstance(op, ast.Eq) and l.value == 1)
                or (isinstance(op, ast.GtE) and l.value == 1)
                or (isinstance(op, ast.Gt) and l.value == 2)
            )
    return False


# ---------------------------------------------------------------------------
# node classifiers
# ---------------------------------------------------------------------------

def divergence_source_call(node: ast.Call, module) -> Optional[str]:
    """Token naming the divergence source when this call mints one."""
    fn = node.func
    leaf = _call_leaf(fn)
    if leaf is None:
        return None
    if leaf in _DIVERGENT_CALL_LEAVES or leaf in DIVERGENT_ATTRS:
        return leaf
    resolved = _resolved(fn, module)
    if resolved in _HOST_IDENT_CALLS or resolved in _WALL_CLOCK_CALLS:
        return resolved
    if resolved in _FS_PROBE_CALLS or leaf in _FS_PROBE_METHOD_LEAVES:
        return resolved or leaf
    if leaf in ("now", "utcnow", "today") and "date" in resolved:
        return resolved
    if resolved in ("os.environ.get", "os.getenv") and node.args:
        key = node.args[0]
        if (
            isinstance(key, ast.Constant)
            and isinstance(key.value, str)
            and _PER_HOST_ENV_RE.search(key.value)
        ):
            return f"os.environ[{key.value!r}]"
    return None


def divergence_source_subscript(node: ast.Subscript, module) -> Optional[str]:
    """``os.environ["LOCAL_RANK"]``-style per-host env reads."""
    base = _resolved(node.value, module)
    if base != "os.environ":
        return None
    key = node.slice
    if (
        isinstance(key, ast.Constant)
        and isinstance(key.value, str)
        and _PER_HOST_ENV_RE.search(key.value)
    ):
        return f"os.environ[{key.value!r}]"
    return None


def symmetry_kill(node: ast.Call) -> bool:
    """The call's RESULT is rank-symmetric by construction (an all-ranks
    merge or an ``agree_*`` pure merge) — taint dies here, including taint
    in the arguments (merging rank-local inputs is the point)."""
    leaf = _call_leaf(node.func)
    if leaf is None:
        return False
    return leaf in _SYMMETRY_KILL_LEAVES or leaf.startswith(_AGREE_PREFIX)


def collective_sink(node: ast.Call, module) -> Optional[str]:
    """Token when this call is a collective every rank must enter together:
    framework collectives by leaf, jax collectives under a jax/lax prefix,
    and ``resize``/``grow`` on a fleet-named receiver (docs/elastic.md)."""
    fn = node.func
    leaf = _call_leaf(fn)
    if leaf is None:
        return None
    if leaf in _FRAMEWORK_COLLECTIVE_LEAVES:
        return leaf
    if leaf in _JAX_COLLECTIVE_LEAVES:
        resolved = _resolved(fn, module)
        if _JAX_PREFIXES & set(resolved.split(".")):
            return leaf
    if leaf in _FLEET_VERB_LEAVES and isinstance(fn, ast.Attribute):
        recv = dotted_name(fn.value) or ""
        if "fleet" in recv.lower():
            return f"fleet.{leaf}"
    return None


def collective_leaves(module, fn_node: ast.AST) -> List[str]:
    """Sorted collective-sink tokens issued directly in ``fn_node``'s own
    body (nested defs excluded — they are their own call-graph nodes)."""
    out: Set[str] = set()
    for sub in iter_own_nodes(fn_node):
        if isinstance(sub, ast.Call):
            tok = collective_sink(sub, module)
            if tok:
                out.add(tok)
    return sorted(out)


# ---------------------------------------------------------------------------
# the per-function fixpoint
# ---------------------------------------------------------------------------

class FunctionTaint:
    """Which local names of one function can hold a rank-divergent value.

    Order-insensitive: the statement walk repeats until the tainted set and
    the pending-callee map stop changing, so uses before (textual) defs in
    loops converge.  Control context is tracked for implicit flows — an
    assignment under a tainted test taints its target (``flag = True`` under
    ``if is_main_process:`` makes ``flag`` divergent), and a ``return``
    under a tainted test makes the RETURN divergent (callers branch on a
    value that differs per rank).

    ``known`` maps callable names (visible names, ``Cls.method`` qualnames)
    to human-readable chains for functions the whole-program fixpoint proved
    divergent-returning; without it, unresolved callee names accumulate as
    *pending* edges in :attr:`via` / :attr:`return_via` for the program
    graph to resolve later.
    """

    MAX_PASSES = 10

    def __init__(self, module, fn_node, known=None, self_prefix=None):
        self.module = module
        self.fn = fn_node
        self.known: Dict[str, str] = dict(known or {})
        self.self_prefix = self_prefix
        self.tainted: Set[str] = set()
        self.via: Dict[str, Set[str]] = {}
        self.return_direct = False
        self.return_via: Set[str] = set()
        self._run()

    # -- public ------------------------------------------------------------
    def expr_tainted(self, node: ast.AST) -> bool:
        t, pending = self.eval(node)
        return t or any(p in self.known for p in pending)

    def describe(self, node: ast.AST) -> str:
        """Best-effort token naming WHY an expression is divergent, for
        finding messages."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                src = divergence_source_call(sub, self.module)
                if src:
                    return f"{src}(...)" if not src.endswith("]") else src
            elif isinstance(sub, ast.Attribute) and sub.attr in DIVERGENT_ATTRS:
                return sub.attr
            elif isinstance(sub, ast.Subscript):
                src = divergence_source_subscript(sub, self.module)
                if src:
                    return src
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                for cand in self.callee_names(sub.func):
                    if cand in self.known:
                        return f"{cand}() [{self.known[cand]}]"
            elif isinstance(sub, ast.Name) and sub.id in self.tainted:
                return sub.id
        return "rank-divergent state"

    # -- fixpoint driver -----------------------------------------------------
    def _snapshot(self):
        return (
            frozenset(self.tainted),
            {k: frozenset(v) for k, v in self.via.items()},
            self.return_direct,
            frozenset(self.return_via),
        )

    def _run(self) -> None:
        for _ in range(self.MAX_PASSES):
            before = self._snapshot()
            self._walk(self.fn.body, False, set(), False)
            if self._snapshot() == before:
                break

    # -- statements ----------------------------------------------------------
    def _walk(self, stmts, ctx_t: bool, ctx_p: Set[str], killed: bool) -> None:
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested defs are their own call-graph nodes
            if isinstance(stmt, ast.Assign):
                self._assign(stmt.targets, stmt.value, ctx_t, ctx_p, killed)
            elif isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    self._assign([stmt.target], stmt.value, ctx_t, ctx_p, killed)
            elif isinstance(stmt, ast.AugAssign):
                self._assign([stmt.target], stmt.value, ctx_t, ctx_p, killed)
            elif isinstance(stmt, ast.Return):
                t, p = (
                    self.eval(stmt.value)
                    if stmt.value is not None
                    else (False, set())
                )
                if not killed:
                    # a return under a divergent test is itself divergent:
                    # which value comes back differs per rank
                    self.return_direct = self.return_direct or t or ctx_t
                    self.return_via |= p | ctx_p
            elif isinstance(stmt, ast.If):
                t, p = self.eval(stmt.test)
                if single_process_conjunct(stmt.test):
                    # the branch never executes multi-process: values born
                    # here cannot diverge a mesh (the PR-13 gate shape); the
                    # else-side entry is uniformly multi-process — symmetric
                    self._walk(stmt.body, False, set(), True)
                    self._walk(stmt.orelse, ctx_t, ctx_p, killed)
                else:
                    bt = ctx_t or (t and not killed)
                    bp = ctx_p | p
                    self._walk(stmt.body, bt, bp, killed)
                    self._walk(stmt.orelse, bt, bp, killed)
            elif isinstance(stmt, ast.While):
                t, p = self.eval(stmt.test)
                if single_process_conjunct(stmt.test):
                    self._walk(stmt.body, False, set(), True)
                else:
                    self._walk(stmt.body, ctx_t or t, ctx_p | p, killed)
                self._walk(stmt.orelse, ctx_t, ctx_p, killed)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                t, p = self.eval(stmt.iter)
                if not killed:
                    self._bind(stmt.target, t, p)
                self._walk(stmt.body, ctx_t or t, ctx_p | p, killed)
                self._walk(stmt.orelse, ctx_t, ctx_p, killed)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    t, p = self.eval(item.context_expr)
                    if item.optional_vars is not None and not killed:
                        self._bind(item.optional_vars, t, p)
                self._walk(stmt.body, ctx_t, ctx_p, killed)
            elif isinstance(stmt, ast.Try) or (
                hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)
            ):
                self._walk(stmt.body, ctx_t, ctx_p, killed)
                for h in stmt.handlers:
                    self._walk(h.body, ctx_t, ctx_p, killed)
                self._walk(stmt.orelse, ctx_t, ctx_p, killed)
                self._walk(stmt.finalbody, ctx_t, ctx_p, killed)
            elif isinstance(stmt, ast.Match):
                t, p = self.eval(stmt.subject)
                for case in stmt.cases:
                    self._walk(case.body, ctx_t or t, ctx_p | p, killed)
            elif isinstance(stmt, ast.Expr):
                self.eval(stmt.value)
            elif isinstance(stmt, ast.Assert):
                self.eval(stmt.test)
            # Raise/Pass/Break/Continue/Import/Global/Delete: nothing tracked

    def _assign(self, targets, value, ctx_t, ctx_p, killed) -> None:
        t, p = self.eval(value)
        if killed:
            return  # single-process-only values never diverge a mesh
        t = t or ctx_t
        p = p | ctx_p
        for tgt in targets:
            self._bind(tgt, t, p)

    def _bind(self, target, t: bool, p: Set[str]) -> None:
        if isinstance(target, ast.Name):
            if t:
                self.tainted.add(target.id)
            if p:
                self.via.setdefault(target.id, set()).update(p)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, t, p)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, t, p)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            # a store INTO a local object taints the object (`cfg.rank = idx`
            # makes every later `cfg.*` read divergent); `self`/`cls` stores
            # are out of scope (documented approximation)
            base = target
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and base.id not in ("self", "cls"):
                self._bind(base, t, p)

    # -- expressions ---------------------------------------------------------
    def eval(self, node) -> Tuple[bool, Set[str]]:
        if node is None or isinstance(node, ast.Constant):
            return False, set()
        if isinstance(node, ast.Name):
            return node.id in self.tainted, set(self.via.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            if node.attr in DIVERGENT_ATTRS:
                return True, set()
            return self.eval(node.value)
        if isinstance(node, ast.Subscript):
            if divergence_source_subscript(node, self.module):
                return True, set()
            t1, p1 = self.eval(node.value)
            t2, p2 = self.eval(node.slice)
            return t1 or t2, p1 | p2
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.NamedExpr):
            t, p = self.eval(node.value)
            self._bind(node.target, t, p)
            return t, p
        if isinstance(node, ast.Lambda):
            return False, set()
        if isinstance(node, ast.IfExp):
            tt, tp = self.eval(node.test)
            bt, bp = self.eval(node.body)
            ot, op = self.eval(node.orelse)
            return tt or bt or ot, tp | bp | op
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            t, p = False, set()
            for gen in node.generators:
                it, ip = self.eval(gen.iter)
                self._bind(gen.target, it, ip)
                t, p = t or it, p | ip
                for cond in gen.ifs:
                    ct, cp = self.eval(cond)
                    t, p = t or ct, p | cp
            elts = (
                (node.key, node.value)
                if isinstance(node, ast.DictComp)
                else (node.elt,)
            )
            for e in elts:
                et, ep = self.eval(e)
                t, p = t or et, p | ep
            return t, p
        # generic fold over child expressions: BoolOp, BinOp, Compare,
        # UnaryOp, f-strings, containers, starred, slices, await, yield
        t, p = False, set()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword)):
                ct, cp = self.eval(child)
                t, p = t or ct, p | cp
        return t, p

    def _eval_call(self, node: ast.Call) -> Tuple[bool, Set[str]]:
        fn = node.func
        if symmetry_kill(node):
            return False, set()
        if divergence_source_call(node, self.module):
            return True, set()
        t, p = False, set()
        if isinstance(fn, ast.Attribute):
            # a method on a divergent object returns divergent data
            # (`record.get("queue_depth")` with record rank-local)
            rt, rp = self.eval(fn.value)
            t, p = t or rt, p | rp
        for arg in node.args:
            at, ap = self.eval(arg)
            t, p = t or at, p | ap
        for kw in node.keywords:
            at, ap = self.eval(kw.value)
            t, p = t or at, p | ap
        for cand in self.callee_names(fn):
            if cand in self.known:
                t = True
            else:
                p.add(cand)
        return t, p

    def callee_names(self, fn: ast.AST) -> List[str]:
        """Candidate callable names a Call's func may resolve to, in the
        edge conventions ``program._resolve_edge`` / the alias maps use:
        bare names for Name calls and ``self.x()`` (plus the enclosing
        ``Cls.x`` qualname when known), full dotted names otherwise."""
        if isinstance(fn, ast.Name):
            return [] if fn.id in _BUILTIN_NOISE else [fn.id]
        if isinstance(fn, ast.Attribute):
            dotted = dotted_name(fn)
            if dotted is None:
                return []
            parts = dotted.split(".")
            if parts[0] in ("self", "cls"):
                if len(parts) != 2:
                    # self.logger.log(): the receiver is an attribute object
                    # of unknown type, not the enclosing class — resolving
                    # the leaf against our own methods would be a lie
                    return []
                leaf = parts[1]
                out = [leaf]
                if self.self_prefix:
                    out.append(f"{self.self_prefix}.{leaf}")
                return out
            return [dotted]
        return []


def return_flow(module, fn_node, self_prefix=None) -> Tuple[bool, List[str]]:
    """Summary-mode digest for one function: (returns-divergent-directly,
    sorted pending callee names whose divergence would make the return
    divergent).  The pending list is capped to bound cache entries."""
    ft = FunctionTaint(module, fn_node, known=None, self_prefix=self_prefix)
    return ft.return_direct, sorted(ft.return_via)[:64]
